"""DistributedOptimizer for torch: per-parameter gradient hooks.

Reference parity: ``horovod/torch/optimizer.py``
(``_DistributedOptimizer``): wraps any ``torch.optim.Optimizer``; when a
parameter's gradient is fully accumulated a hook fires an async
allreduce named ``DistributedOptimizer.gradient/<param>``; ``step()``
synchronizes every outstanding handle (writing the averaged gradient
back in place) before the inner optimizer step applies it.  Supports
``backward_passes_per_step`` (local gradient aggregation: only every
k-th backward triggers communication) and gradient compression.
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Dict, Iterator, Optional, Tuple

import torch

from ..ops.xla_ops import AVERAGE
from . import mpi_ops
from ..common import basics
from .compression import Compression


# Constructed in the same program order on every rank, so the instance
# index is cross-rank deterministic and keeps concurrently active
# optimizers' group names from colliding in the tensor queue.
_instance_ids = itertools.count()


class _DistributedOptimizer:
    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op=AVERAGE,
                 gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0,
                 groups=None,
                 sparse_as_dense: bool = False,
                 process_set=None):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self._predivide = gradient_predivide_factor
        self._prescale = 1.0 / gradient_predivide_factor \
            if gradient_predivide_factor != 1.0 else 1.0
        self._postscale = gradient_predivide_factor \
            if gradient_predivide_factor != 1.0 else 1.0
        self._instance_id = next(_instance_ids)
        self._sparse_as_dense = sparse_as_dense
        self.backward_passes_per_step = backward_passes_per_step
        self._require_sync = True

        # Reference surface: ``groups`` is an int (same as num_groups)
        # or an explicit list of parameter lists; params outside any
        # explicit group keep their individual allreduce.
        if isinstance(groups, int):
            num_groups, groups = groups, None
        elif groups is not None and not isinstance(groups, (list, tuple)):
            raise ValueError(
                "groups must be an int or a list of parameter lists")
        self._num_groups = num_groups
        self._explicit_groups = groups
        self._group_of: Dict[torch.Tensor, int] = {}
        self._group_members: Dict[int, list] = {}
        self._group_ready: Dict[int, list] = {}

        if named_parameters is not None:
            named = list(named_parameters)
            # Reference parity: a partial mapping would leave some params
            # falling back to hook order for the grouped wire sort, which
            # is not cross-rank deterministic — upstream rejects it too.
            names = [name for name, _ in named]
            if len(set(names)) < len(names):
                dup = sorted({n for n in names if names.count(n) > 1})
                raise ValueError(
                    "named_parameters contains duplicate names: %s"
                    % ", ".join(dup))
            covered = {id(p) for _, p in named}
            missing = sum(
                1 for group in optimizer.param_groups
                for p in group["params"] if id(p) not in covered)
            if missing:
                raise ValueError(
                    "named_parameters was specified, but %d of the "
                    "optimizer's parameters are not named; pass "
                    "model.named_parameters() covering every parameter "
                    "in optimizer.param_groups" % missing)
        else:
            named = []
            for gi, group in enumerate(optimizer.param_groups):
                for pi, p in enumerate(group["params"]):
                    named.append(("group%d.param%d" % (gi, pi), p))
        self._param_names: Dict[torch.Tensor, str] = {
            p: name for name, p in named}
        self._handles: Dict[torch.Tensor, object] = {}
        self._passes: Dict[torch.Tensor, int] = {}
        self._grad_ctx: Dict[torch.Tensor, object] = {}
        self._hook_handles = []
        if basics.size() > 1:
            self._register_hooks()
            self._assign_groups()

    # -- reference surface -------------------------------------------------

    def __getattr__(self, item):
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    @property
    def state(self):
        return self._opt.state

    def _register_hooks(self):
        for group in self._opt.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _assign_groups(self):
        """Partition hooked params into grouped-allreduce buckets
        (reference ``num_groups``/``groups``: group members negotiate
        and fuse atomically via ``hvd.grouped_allreduce``)."""
        hooked = [p for group in self._opt.param_groups
                  for p in group["params"] if p.requires_grad]
        if self._explicit_groups is not None:
            hooked_ids = {id(p) for p in hooked}
            seen = set()
            for gid, members in enumerate(self._explicit_groups):
                for p in members:
                    if id(p) in seen:
                        raise ValueError(
                            "parameter appears in more than one group")
                    seen.add(id(p))
                    if not p.requires_grad:
                        continue
                    if id(p) not in hooked_ids:
                        # A member with no hook would keep its group from
                        # ever completing during backward.
                        raise ValueError(
                            "groups contains a parameter that is not in "
                            "this optimizer's param_groups")
                    self._group_of[p] = gid
                    self._group_members.setdefault(gid, []).append(p)
        elif self._num_groups > 0:
            n = min(self._num_groups, len(hooked)) or 1
            size, rem = divmod(len(hooked), n)
            start = 0
            for gid in range(n):
                stop = start + size + (1 if gid < rem else 0)
                for p in hooked[start:stop]:
                    self._group_of[p] = gid
                    self._group_members.setdefault(gid, []).append(p)
                start = stop

    def _make_hook(self):
        def hook(p: torch.Tensor):
            self._passes[p] = self._passes.get(p, 0) + 1
            if self._passes[p] < self.backward_passes_per_step:
                return
            self._passes[p] = 0
            gid = self._group_of.get(p)
            if gid is None:
                self._allreduce_grad_async(p)
                return
            ready = self._group_ready.setdefault(gid, [])
            if p in self._handles or any(p is q for q in ready):
                raise AssertionError(
                    "gradient for a grouped parameter produced twice "
                    "without step()/synchronize()")
            ready.append(p)
            if len(ready) == len(self._group_members[gid]):
                self._fire_group(gid)
        return hook

    def _prepare_grad(self, p: torch.Tensor) -> torch.Tensor:
        grad = p.grad
        if grad.is_sparse:
            if not self._sparse_as_dense:
                # Only the grouped path lands here; singles route to
                # the sparse wire in _allreduce_grad_async.
                raise ValueError(
                    "sparse gradients in grouped buckets need "
                    "DistributedOptimizer(sparse_as_dense=True)")
            grad = grad.coalesce().to_dense()
        if self.backward_passes_per_step > 1:
            grad = grad / float(self.backward_passes_per_step)
        return grad

    def _allreduce_grad_async(self, p: torch.Tensor):
        name = "DistributedOptimizer.gradient/%s" % \
            self._param_names.get(p, "param%d" % id(p))
        if p.grad.is_sparse and not self._sparse_as_dense:
            # Reference default for sparse grads: indices/values ride
            # two ragged allgathers, duplicates summed on coalesce.
            grad = p.grad
            if self.backward_passes_per_step > 1:
                grad = grad / float(self.backward_passes_per_step)
            self._grad_ctx[p] = None
            self._handles[p] = mpi_ops.sparse_allreduce_async(
                grad, name=name, op=self._op,
                process_set=self._process_set)
            return
        wire, ctx = self._compression.compress(self._prepare_grad(p))
        self._grad_ctx[p] = ctx
        self._handles[p] = mpi_ops.allreduce_async(
            wire, name=name, op=self._op, prescale_factor=self._prescale,
            postscale_factor=self._postscale,
            process_set=self._process_set)

    def _fire_group(self, gid: int):
        params = self._group_ready.pop(gid, [])
        if not params:
            return
        # Wire order must match across ranks; hook order is autograd-
        # dependent, so sort by the cross-rank-deterministic name.
        params.sort(key=lambda p: self._param_names.get(p, ""))
        wires = []
        for p in params:
            wire, ctx = self._compression.compress(self._prepare_grad(p))
            self._grad_ctx[p] = ctx
            wires.append(wire)
        handles = mpi_ops.grouped_allreduce_async(
            wires,
            name="DistributedOptimizer.o%d.group%d"
                 % (self._instance_id, gid),
            op=self._op, prescale_factor=self._prescale,
            postscale_factor=self._postscale,
            process_set=self._process_set)
        for p, h in zip(params, handles):
            self._handles[p] = h

    def synchronize(self):
        """Wait for every outstanding gradient allreduce and install the
        results (reference ``optimizer.synchronize()``)."""
        # Groups left incomplete (frozen params, conditional branches)
        # still fire over whichever members produced gradients.
        for gid in list(self._group_ready):
            self._fire_group(gid)
        for p, handle in list(self._handles.items()):
            out = handle.wait()
            if isinstance(handle, mpi_ops.SparseTorchHandle):
                p.grad = out  # averaged, still sparse
                continue
            out = self._compression.decompress(out, self._grad_ctx.get(p))
            if p.grad.is_sparse:
                p.grad = out.reshape(p.grad.shape)
            else:
                p.grad.data.copy_(out.reshape(p.grad.shape))
        self._handles.clear()
        self._grad_ctx.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Reference API: inside this context ``step()`` will not call
        ``synchronize()`` again (for use after a manual call)."""
        self._require_sync = False
        try:
            yield
        finally:
            self._require_sync = True

    def step(self, closure=None):
        if self._require_sync and basics.size() > 1:
            # Any param whose hook never fired this step (frozen layers,
            # conditional branches) simply has no handle.
            self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles or any(self._group_ready.values()):
            raise AssertionError(
                "zero_grad called with outstanding gradient allreduces "
                "(or partially-ready grouped buckets); call "
                "optimizer.step() or synchronize() first")
        return self._opt.zero_grad(*args, **kwargs)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, *args, **kwargs):
        return self._opt.load_state_dict(*args, **kwargs)

    def add_param_group(self, group):
        self._opt.add_param_group(group)
        gi = len(self._opt.param_groups) - 1
        for pi, p in enumerate(group["params"]):
            # Deterministic cross-rank name (id() would differ per
            # process and wedge the name-keyed negotiation).
            self._param_names.setdefault(
                p, "group%d.param%d" % (gi, pi))
        if basics.size() > 1:
            for p in group["params"]:
                if p.requires_grad and p not in self._passes:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterator[Tuple[str,
                                                    torch.Tensor]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=AVERAGE,
                         gradient_predivide_factor: float = 1.0,
                         num_groups: int = 0,
                         groups=None,
                         sparse_as_dense: bool = False,
                         process_set=None) -> _DistributedOptimizer:
    """Wrap a torch optimizer for data-parallel training (reference
    ``hvd.DistributedOptimizer``)."""
    return _DistributedOptimizer(
        optimizer, named_parameters, compression,
        backward_passes_per_step, op, gradient_predivide_factor,
        num_groups, groups, sparse_as_dense, process_set)
