"""DistributedOptimizer for torch: per-parameter gradient hooks.

Reference parity: ``horovod/torch/optimizer.py``
(``_DistributedOptimizer``): wraps any ``torch.optim.Optimizer``; when a
parameter's gradient is fully accumulated a hook fires an async
allreduce named ``DistributedOptimizer.gradient/<param>``; ``step()``
synchronizes every outstanding handle (writing the averaged gradient
back in place) before the inner optimizer step applies it.  Supports
``backward_passes_per_step`` (local gradient aggregation: only every
k-th backward triggers communication) and gradient compression.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator, Optional, Tuple

import torch

from ..ops.xla_ops import AVERAGE
from . import mpi_ops
from ..common import basics
from .compression import Compression


class _DistributedOptimizer:
    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op=AVERAGE,
                 gradient_predivide_factor: float = 1.0,
                 process_set=None):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self._predivide = gradient_predivide_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._require_sync = True

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            for gi, group in enumerate(optimizer.param_groups):
                for pi, p in enumerate(group["params"]):
                    named.append(("group%d.param%d" % (gi, pi), p))
        self._param_names: Dict[torch.Tensor, str] = {
            p: name for name, p in named}
        self._handles: Dict[torch.Tensor, object] = {}
        self._passes: Dict[torch.Tensor, int] = {}
        self._grad_ctx: Dict[torch.Tensor, object] = {}
        self._hook_handles = []
        if basics.size() > 1:
            self._register_hooks()

    # -- reference surface -------------------------------------------------

    def __getattr__(self, item):
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    @property
    def state(self):
        return self._opt.state

    def _register_hooks(self):
        for group in self._opt.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p: torch.Tensor):
            self._passes[p] = self._passes.get(p, 0) + 1
            if self._passes[p] < self.backward_passes_per_step:
                return
            self._passes[p] = 0
            self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p: torch.Tensor):
        name = "DistributedOptimizer.gradient/%s" % \
            self._param_names.get(p, "param%d" % id(p))
        grad = p.grad
        if self.backward_passes_per_step > 1:
            grad = grad / float(self.backward_passes_per_step)
        wire, ctx = self._compression.compress(grad)
        prescale = 1.0 / self._predivide if self._predivide != 1.0 else 1.0
        postscale = self._predivide if self._predivide != 1.0 else 1.0
        self._grad_ctx[p] = ctx
        self._handles[p] = mpi_ops.allreduce_async(
            wire, name=name, op=self._op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=self._process_set)

    def synchronize(self):
        """Wait for every outstanding gradient allreduce and install the
        results (reference ``optimizer.synchronize()``)."""
        for p, handle in list(self._handles.items()):
            out = handle.wait()
            out = self._compression.decompress(out, self._grad_ctx.get(p))
            p.grad.data.copy_(out.reshape(p.grad.shape))
        self._handles.clear()
        self._grad_ctx.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Reference API: inside this context ``step()`` will not call
        ``synchronize()`` again (for use after a manual call)."""
        self._require_sync = False
        try:
            yield
        finally:
            self._require_sync = True

    def step(self, closure=None):
        if self._require_sync and basics.size() > 1:
            # Any param whose hook never fired this step (frozen layers,
            # conditional branches) simply has no handle.
            self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "zero_grad called with outstanding gradient allreduces; "
                "call optimizer.step() or synchronize() first")
        return self._opt.zero_grad(*args, **kwargs)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, *args, **kwargs):
        return self._opt.load_state_dict(*args, **kwargs)

    def add_param_group(self, group):
        self._opt.add_param_group(group)
        gi = len(self._opt.param_groups) - 1
        for pi, p in enumerate(group["params"]):
            # Deterministic cross-rank name (id() would differ per
            # process and wedge the name-keyed negotiation).
            self._param_names.setdefault(
                p, "group%d.param%d" % (gi, pi))
        if basics.size() > 1:
            for p in group["params"]:
                if p.requires_grad and p not in self._passes:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterator[Tuple[str,
                                                    torch.Tensor]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=AVERAGE,
                         gradient_predivide_factor: float = 1.0,
                         process_set=None) -> _DistributedOptimizer:
    """Wrap a torch optimizer for data-parallel training (reference
    ``hvd.DistributedOptimizer``)."""
    return _DistributedOptimizer(
        optimizer, named_parameters, compression,
        backward_passes_per_step, op, gradient_predivide_factor,
        process_set)
