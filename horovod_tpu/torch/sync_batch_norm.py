"""Synchronized BatchNorm across ranks.

Reference parity: ``horovod/torch/sync_batch_norm.py`` — batch statistics
are computed over the GLOBAL batch by allreducing per-rank sums and
square-sums, and the backward pass allreduces the gradient sums so
``grad_input`` matches single-process BN over the concatenated batch
(the reference uses the same two-collective forward/backward structure).
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from ..common import basics
from . import mpi_ops
from ..ops.xla_ops import SUM


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, mean, invstd, total_count,
                tag):
        shape = [1, input.size(1)] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        ctx.save_for_backward(input, weight, mean, invstd)
        ctx.total_count = total_count
        ctx.tag = tag
        if weight is not None:
            return xhat * weight.view(shape) + bias.view(shape)
        return xhat

    @staticmethod
    def backward(ctx, grad_out):
        input, weight, mean, invstd = ctx.saved_tensors
        shape = [1, input.size(1)] + [1] * (input.dim() - 2)
        dims = [0] + list(range(2, input.dim()))
        xhat = (input - mean.view(shape)) * invstd.view(shape)

        g = grad_out if weight is None else \
            grad_out * weight.view(shape)
        sum_g = g.sum(dim=dims)
        sum_gx = (g * xhat).sum(dim=dims)
        packed = torch.cat([sum_g, sum_gx]).to(torch.float64)
        packed = mpi_ops.allreduce(
            packed, op=SUM, name="sync_batch_norm.bwd.%s" % ctx.tag)
        c = sum_g.numel()
        sum_g = packed[:c].to(input.dtype)
        sum_gx = packed[c:].to(input.dtype)

        n = float(ctx.total_count)
        grad_input = invstd.view(shape) * (
            g - (sum_g.view(shape) + xhat * sum_gx.view(shape)) / n)
        grad_weight = (grad_out * xhat).sum(dim=dims) \
            if weight is not None else None
        grad_bias = grad_out.sum(dim=dims) if weight is not None else None
        return grad_input, grad_weight, grad_bias, None, None, None, None


class SyncBatchNorm(_BatchNorm):
    """Drop-in BatchNorm that synchronizes statistics across the world
    during training (``hvd.SyncBatchNorm``)."""

    _tag_counter = 0

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        SyncBatchNorm._tag_counter += 1
        self._tag = "bn%d" % SyncBatchNorm._tag_counter

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError("expected at least 2D input")

    @classmethod
    def convert_sync_batchnorm(cls, module: torch.nn.Module
                               ) -> torch.nn.Module:
        """Recursively replace BatchNorm layers (reference
        ``convert_sync_batchnorm`` shape)."""
        out = module
        if isinstance(module, _BatchNorm) and not isinstance(module, cls):
            out = cls(module.num_features, module.eps, module.momentum,
                      module.affine, module.track_running_stats)
            if module.affine:
                with torch.no_grad():
                    out.weight.copy_(module.weight)
                    out.bias.copy_(module.bias)
            out.running_mean = module.running_mean
            out.running_var = module.running_var
            out.num_batches_tracked = module.num_batches_tracked
        for name, child in module.named_children():
            out.add_module(name, cls.convert_sync_batchnorm(child))
        return out

    def forward(self, input: torch.Tensor) -> torch.Tensor:
        self._check_input_dim(input)
        world = basics.size() if basics.is_initialized() else 1
        if not self.training or world <= 1:
            return super().forward(input)

        dims = [0] + list(range(2, input.dim()))
        with torch.no_grad():
            count = torch.tensor(
                [input.numel() // input.size(1)], dtype=torch.float64)
            local_sum = input.sum(dim=dims).to(torch.float64)
            local_sqsum = (input * input).sum(dim=dims).to(torch.float64)
            packed = torch.cat([count, local_sum, local_sqsum])
            packed = mpi_ops.allreduce(
                packed, op=SUM, name="sync_batch_norm.fwd.%s" % self._tag)
            n = float(packed[0])
            mean = (packed[1:1 + self.num_features] / n).to(input.dtype)
            sqmean = (packed[1 + self.num_features:] / n).to(input.dtype)
            var = sqmean - mean * mean
            invstd = torch.rsqrt(var + self.eps)

            if self.track_running_stats:
                m = self.momentum if self.momentum is not None else 0.1
                unbiased = var * (n / max(n - 1.0, 1.0))
                self.running_mean.mul_(1 - m).add_(mean, alpha=m)
                self.running_var.mul_(1 - m).add_(unbiased, alpha=m)
                self.num_batches_tracked += 1

        return _SyncBatchNormFn.apply(
            input, self.weight if self.affine else None,
            self.bias if self.affine else None, mean, invstd, n,
            self._tag)
