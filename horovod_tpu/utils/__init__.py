"""Utility subsystems: timeline, stall inspector, autotuner, adasum."""
