"""Adasum reduction: scale-insensitive gradient merging.

Equivalent of the reference's ``horovod/common/ops/adasum/adasum.h`` +
``adasum_mpi.cc``: instead of summing gradients, Adasum merges pairs with a
projection rule that is robust to learning-rate scaling:

    adasum(a, b) = (1 - <a,b> / (2 |a|^2)) a  +  (1 - <a,b> / (2 |b|^2)) b

applied in a recursive-halving binary tree over ranks (requires a
power-of-two world, as the reference does for its dimension-exchange).

The reference implements this as MPI sendrecv rounds; TPU-natively the
whole tree evaluates as one XLA program over the stacked rank axis (a
log2(n)-step reduction with large fused vector math on the VPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def adasum_pair(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Merge two same-shaped gradient tensors with the Adasum rule."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    ca = 1.0 - dot / jnp.maximum(2.0 * na, 1e-30)
    cb = 1.0 - dot / jnp.maximum(2.0 * nb, 1e-30)
    out = ca * af + cb * bf
    return out.reshape(a.shape).astype(a.dtype)


def _tree_reduce(stacked: jnp.ndarray) -> jnp.ndarray:
    n = stacked.shape[0]
    if n & (n - 1):
        raise ValueError(
            "Adasum requires a power-of-two number of ranks (got %d), as "
            "in the reference's recursive-halving implementation" % n)
    while stacked.shape[0] > 1:
        half = stacked.shape[0] // 2
        merged = jax.vmap(adasum_pair)(stacked[:half], stacked[half:])
        stacked = merged
    return stacked[0]


_tree_reduce_jit = jax.jit(_tree_reduce)


def adasum_reduce_stacked(stacked) -> jnp.ndarray:
    """Reduce a rank-major stacked [size, ...] array with Adasum."""
    return _tree_reduce_jit(jnp.asarray(stacked))
