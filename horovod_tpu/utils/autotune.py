"""Online autotuner for fusion threshold and cycle time.

Equivalent of the reference's ``horovod/common/parameter_manager.cc`` +
``horovod/common/optim/bayesian_optimization.cc`` / ``gaussian_process.cc``:
when ``HOROVOD_AUTOTUNE=1``, the engine scores each sample of
(fusion_threshold, cycle_time) by observed throughput (bytes reduced per
second), and a Gaussian-process surrogate with an expected-improvement
acquisition proposes the next sample.  After convergence (or
``HOROVOD_AUTOTUNE_STEPS`` samples) the best point is pinned.

The search space mirrors the reference: fusion threshold over
{0..64} MiB-scale powers of two, cycle time over 1..25 ms.  Scores and
samples are appended to ``HOROVOD_AUTOTUNE_LOG`` as CSV when set.

A native C++ implementation with the same algorithm lives in
``horovod_tpu/core`` for the TCP world; this module drives the in-process
engine and is also importable for tests of the math itself.
"""

from __future__ import annotations

import math
import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Search space (log2 bytes, ms).
_FUSION_CHOICES = [1 << p for p in range(20, 28)]  # 1 MiB .. 128 MiB
_CYCLE_CHOICES = [1.0, 2.5, 5.0, 10.0, 25.0]


class GaussianProcess:
    """Minimal RBF-kernel GP regressor (reference: gaussian_process.cc)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6,
                 alpha: float = 1e-10):
        self.length_scale = length_scale
        self.noise = noise
        self.alpha = alpha
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._l: Optional[np.ndarray] = None
        self._a: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d / (self.length_scale ** 2))

    def _log_marginal_likelihood(self, ls: float) -> float:
        """LML of the stored (x, y) at a candidate length-scale."""
        saved = self.length_scale
        try:
            self.length_scale = ls
            k = self._kernel(self._x, self._x)
        finally:
            self.length_scale = saved
        k[np.diag_indices_from(k)] += self.noise + self.alpha
        try:
            low = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        a = np.linalg.solve(low.T, np.linalg.solve(low, self._y))
        n = len(self._y)
        return float(-0.5 * self._y @ a
                     - np.log(np.diag(low)).sum()
                     - 0.5 * n * math.log(2.0 * math.pi))

    def optimize_length_scale(self, lo: float = 0.1, hi: float = 10.0,
                              iters: int = 24):
        """Max-marginal-likelihood length-scale via golden-section
        search on the 1-D log length-scale (reference fits kernel
        hyperparameters with lbfgs in optim/; one bounded 1-D search
        needs no lbfgs dependency)."""
        a, b = math.log(lo), math.log(hi)
        inv_phi = (math.sqrt(5.0) - 1.0) / 2.0
        c = b - inv_phi * (b - a)
        d = a + inv_phi * (b - a)
        fc = self._log_marginal_likelihood(math.exp(c))
        fd = self._log_marginal_likelihood(math.exp(d))
        for _ in range(iters):
            if fc > fd:
                b, d, fd = d, c, fc
                c = b - inv_phi * (b - a)
                fc = self._log_marginal_likelihood(math.exp(c))
            else:
                a, c, fc = c, d, fd
                d = a + inv_phi * (b - a)
                fd = self._log_marginal_likelihood(math.exp(d))
        self.length_scale = math.exp((a + b) / 2.0)
        return self.length_scale

    def fit(self, x: np.ndarray, y: np.ndarray,
            optimize_length_scale: bool = False):
        self._x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        self._y = np.asarray(y, dtype=np.float64)
        if optimize_length_scale and len(self._y) >= 4:
            self.optimize_length_scale()
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise + self.alpha
        self._l = np.linalg.cholesky(k)
        self._a = np.linalg.solve(
            self._l.T, np.linalg.solve(self._l, self._y))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        ks = self._kernel(x, self._x)
        mu = ks @ self._a
        v = np.linalg.solve(self._l, ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return mu, np.sqrt(var)


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference: bayesian_optimization.cc)."""
    z = (mu - best - xi) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2.0 * math.pi)
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """GP + EI over the discrete (fusion, cycle) grid."""

    def __init__(self):
        self.grid = np.array(
            [[math.log2(f), math.log2(c + 1.0)]
             for f in _FUSION_CHOICES for c in _CYCLE_CHOICES])
        self.points: List[np.ndarray] = []
        self.scores: List[float] = []
        self.gp = GaussianProcess(length_scale=1.5)

    def _normalize(self):
        y = np.asarray(self.scores)
        s = y.std()
        return (y - y.mean()) / (s if s > 0 else 1.0)

    def record(self, point_idx: int, score: float):
        self.points.append(self.grid[point_idx])
        self.scores.append(score)

    def next_index(self) -> int:
        if len(self.scores) < 2:
            # Bootstrap with spread-out samples.
            return [0, len(self.grid) - 1][len(self.scores)]
        self.gp.fit(np.stack(self.points), self._normalize(),
                    optimize_length_scale=True)
        mu, sigma = self.gp.predict(self.grid)
        ei = expected_improvement(mu, sigma, float(self._normalize().max()))
        return int(np.argmax(ei))

    def best_index(self) -> int:
        by_point = {}
        for p, s in zip(self.points, self.scores):
            by_point.setdefault(tuple(p), []).append(s)
        best_p = max(by_point, key=lambda p: np.mean(by_point[p]))
        return int(np.argmin(((self.grid - np.array(best_p)) ** 2).sum(1)))


class KernelBlockTuner:
    """Categorical argmax-by-mean tuner for kernel launch parameters
    (flash-attention block shapes).  The search space is a handful of
    discrete choices, so unlike the fusion/cycle surface no GP is
    warranted: repeated samples per choice are averaged and the best
    mean wins.  A native twin (``KernelTuner`` in
    ``core/src/parameter_manager.cc``) aggregates the same scores on
    the TCP core for cross-run observability; this class is the
    in-process source of truth for the sweep
    (``ops.pallas_kernels.autotune_flash_blocks``)."""

    def __init__(self, choices):
        self.choices = list(choices)
        if not self.choices:
            raise ValueError("KernelBlockTuner needs at least 1 choice")
        self._sums = np.zeros(len(self.choices), np.float64)
        self._counts = np.zeros(len(self.choices), np.int64)

    def record(self, index: int, score: float):
        if not 0 <= index < len(self.choices):
            raise IndexError("choice index %d out of range [0, %d)"
                             % (index, len(self.choices)))
        self._sums[index] += float(score)
        self._counts[index] += 1

    def samples(self) -> int:
        return int(self._counts.sum())

    def scores_vector(self) -> np.ndarray:
        """Per-choice mean scores; unsampled choices are -inf so they
        can never win an argmax (and so the vector has a fixed length
        for a deterministic cross-rank reduction)."""
        with np.errstate(invalid="ignore"):
            means = self._sums / np.maximum(self._counts, 1)
        return np.where(self._counts > 0, means, -np.inf)

    def best(self):
        if self.samples() == 0:
            raise RuntimeError("no samples recorded")
        return self.choices[int(np.argmax(self.scores_vector()))]


class PlanTuner:
    """GP/EI proposer over one (op, size_class)'s candidate plan grid —
    the widened search space of ROADMAP item 1: hier-vs-flat leg
    choice x cross-host codec engagement (``utils/plancache.py`` builds
    the candidate list from what the world actually supports).

    Each candidate is a coordinate (e.g. ``(hier, codec)`` in {0,1}^2);
    every candidate is bootstrapped once, then the same GP surrogate +
    expected-improvement acquisition as the fusion/cycle tuner proposes
    further samples until ``max_samples``, after which :meth:`best` is
    the argmax-by-mean.  SPMD contract: in a multi-member world the
    caller must cross-rank AVERAGE each score before :meth:`record`
    (``tune_collective_plans`` does) — proposals and the final argmax
    are then pure functions of identical state on every member, so all
    members pin the same plan.
    """

    def __init__(self, coords: Sequence[Sequence[float]],
                 max_samples: Optional[int] = None, xi: float = 0.01):
        self.coords = np.atleast_2d(np.asarray(coords, np.float64))
        # atleast_2d turns an empty list into shape (1, 0); size catches
        # that where len() would not.
        self.n = len(self.coords) if self.coords.size else 0
        if self.n < 1:
            raise ValueError("PlanTuner needs at least 1 candidate")
        self.max_samples = int(max_samples or max(2 * self.n, self.n + 1))
        self.xi = float(xi)
        self.points: List[int] = []
        self.scores: List[float] = []
        self.gp = GaussianProcess(length_scale=0.8)

    @property
    def samples(self) -> int:
        return len(self.scores)

    @property
    def converged(self) -> bool:
        if self.n == 1:
            return self.samples >= 1
        return self.samples >= self.max_samples

    def propose(self) -> int:
        """Next candidate index to sample: each candidate once first
        (deterministic bootstrap), then EI over the grid."""
        sampled = set(self.points)
        for i in range(self.n):
            if i not in sampled:
                return i
        y = np.asarray(self.scores)
        s = y.std()
        yn = (y - y.mean()) / (s if s > 0 else 1.0)
        self.gp.fit(self.coords[self.points], yn)
        mu, sigma = self.gp.predict(self.coords)
        ei = expected_improvement(mu, sigma, float(yn.max()), self.xi)
        return int(np.argmax(ei))

    def record(self, index: int, score: float):
        if not 0 <= index < self.n:
            raise IndexError("candidate index %d out of range [0, %d)"
                             % (index, self.n))
        self.points.append(int(index))
        self.scores.append(float(score))

    def mean_scores(self) -> List[Optional[float]]:
        by: dict = {}
        for p, s in zip(self.points, self.scores):
            by.setdefault(p, []).append(s)
        return [float(np.mean(by[i])) if i in by else None
                for i in range(self.n)]

    def best(self) -> int:
        if not self.scores:
            raise RuntimeError("no samples recorded")
        means = self.mean_scores()
        return int(max((i for i in range(self.n)
                        if means[i] is not None),
                       key=lambda i: means[i]))


class AutotuneLog:
    """Crash-safe autotune CSV writer (the r11 journal conventions).

    The old ``open(path, "w")`` writer clobbered peers' logs and
    interleaved partial lines across a multi-process world.  This one
    rank-stamps the filename (``<path>.r<rank>``, pid fallback — one
    writer per file, like ``events-<writer>.jsonl``) and appends each
    record as ONE ``os.write`` on an ``O_APPEND`` fd: concurrent
    writers can interleave lines, never bytes, and a crash tears at
    most nothing (a line is a single atomic append).  The header is
    written only when this writer's file is empty, so restarted runs
    append instead of restamping."""

    HEADER = "sample,fusion_bytes,cycle_ms,score_bytes_per_s"

    def __init__(self, path: str, tag: Optional[str] = None):
        if tag is None:
            rank = os.environ.get("HOROVOD_RANK")
            tag = "r%s" % rank if rank is not None \
                else "pid%d" % os.getpid()
        self.path = "%s.%s" % (path, tag)
        self._fd: Optional[int] = None
        try:
            self._fd = os.open(
                self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
            if os.fstat(self._fd).st_size == 0:
                self.write_line(self.HEADER)
        except OSError:
            # A bad log path degrades observability, never tuning.
            self._fd = None

    def write_line(self, line: str):
        if self._fd is None:
            return
        try:
            os.write(self._fd, (line + "\n").encode())
        except OSError:
            pass

    def close(self):
        if self._fd is not None:
            try:
                os.close(self._fd)
            finally:
                self._fd = None

    def __del__(self):
        # GC-finalizer parity with the file-object writer this
        # replaced: a ParameterManager dropped at shutdown/re-init
        # must not leak its O_APPEND fd across elastic init cycles.
        self.close()


class ParameterManager:
    """Drives sampling from the engine's cycle loop (parameter_manager.cc).

    ``observe(bytes, secs)`` is called once per non-empty cycle; samples are
    scored by aggregate throughput over ``steps_per_sample`` cycles.

    ``warm_start=(fusion, cycle_ms, converged)`` adopts a persisted
    plan's operating point (``utils/plancache.py``): a converged plan
    freezes the tuner entirely (warm-up skipped — the rerun cold-starts
    where the last run ended instead of re-walking the grid); an
    unconverged one runs the adopted point through a single warm-up
    cycle (the fresh process's compile skew must not enter the GP) and
    then resumes the sweep.
    """

    def __init__(self, fusion_threshold: int, cycle_time_ms: float,
                 log_path: Optional[str] = None, warmup: int = 3,
                 steps_per_sample: int = 10, max_samples: int = 30,
                 warm_start: Optional[Tuple[int, float, bool]] = None,
                 log_tag: Optional[str] = None):
        self.bo = BayesianOptimizer()
        self.fusion_threshold = fusion_threshold
        self.cycle_time_ms = cycle_time_ms
        self.warmup = warmup
        self.steps_per_sample = steps_per_sample
        self.max_samples = max_samples
        self._log = AutotuneLog(log_path, log_tag) if log_path else None
        self._cycle_bytes = 0.0
        self._max_secs = 0.0
        self._cycles_seen = 0
        self._last_obs_end = 0.0
        self._samples_done = 0
        self._current_idx: Optional[int] = None
        self.frozen = False
        if warm_start is not None:
            f, c, converged = warm_start
            self.fusion_threshold = int(f)
            self.cycle_time_ms = float(c)
            # Converged: nothing left to sample, skip warm-up outright.
            # Unconverged: keep ONE warm-up cycle — the rerun's first
            # observation carries fresh-process compile skew, exactly
            # what the warm-up window exists to discard.
            self.warmup = 0 if converged else min(int(warmup), 1)
            self.frozen = bool(converged)
            if self._log:
                self._log.write_line(
                    "# warm-start: fusion=%d cycle=%.3f converged=%d"
                    % (self.fusion_threshold, self.cycle_time_ms,
                       int(self.frozen)))

    @property
    def samples_done(self) -> int:
        return self._samples_done

    @property
    def warmup_left(self) -> int:
        return max(int(self.warmup), 0)

    def _apply(self, idx: int):  # graftlint: spmd-uniform -- in-process tuner: ParameterManager is installed only by the single-process engine (common/basics.py, mode == "inprocess"), so there is no peer to diverge from; the multi-member planes tune through tune_collective_plans' cross-rank-averaged sweep instead
        f_log, c_log = self.bo.grid[idx]
        self.fusion_threshold = int(2 ** f_log)
        self.cycle_time_ms = float(2 ** c_log - 1.0)
        self._current_idx = idx

    def observe(self, nbytes: int, secs: float):  # graftlint: spmd-uniform -- in-process tuner: installed only by the single-process engine (common/basics.py, mode == "inprocess"); its wall-clock scores feed a private BO with no peer to diverge from, and the multi-member sweep (tune_collective_plans) cross-rank-averages before ITS tuner sees a score
        if self.frozen:
            return
        if self.warmup > 0:
            self.warmup -= 1
            return
        if self._current_idx is None:
            self._apply(self.bo.next_index())
        now = time.monotonic()
        s = max(secs, 0.0)
        if self._cycles_seen > 0:
            # LONG application idle inside a window (eval pauses, data
            # stalls) is not the candidate's fault — EXCLUDE it from
            # the scored denominator (shift the window start forward)
            # rather than discarding the window, so workloads whose
            # steps are spaced beyond the threshold still fill windows
            # and record samples.  Normal inter-step compute gaps stay
            # below the threshold and keep counting as wall time.
            gap = (now - self._last_obs_end) - s
            if gap > max(5.0, 50.0 * self.cycle_time_ms / 1e3):
                self._sample_t0 += gap
        if self._cycles_seen == 0:
            # observe() runs at cycle END; backdate by this cycle's
            # active time so the window covers every accumulated cycle.
            self._sample_t0 = now - s
        self._last_obs_end = now
        self._cycle_bytes += nbytes
        self._max_secs = max(self._max_secs, secs, 1e-9)
        self._cycles_seen += 1
        if self._cycles_seen < self.steps_per_sample:
            return
        # Score by WALL time across the sample window: the cycle pause
        # and any contention the candidate point causes must count, or
        # short cycle times look free.  Observations may overlap
        # (pipelined device groups), so the clock guard is the LONGEST
        # single observation, never their sum.
        wall = max(time.monotonic() - self._sample_t0,
                   self._max_secs, 1e-9)
        score = self._cycle_bytes / wall
        self.bo.record(self._current_idx, score)
        self._samples_done += 1
        if self._log:
            self._log.write_line("%d,%d,%.3f,%.1f" % (
                self._samples_done, self.fusion_threshold,
                self.cycle_time_ms, score))
        self._cycle_bytes = self._max_secs = 0.0
        self._cycles_seen = 0
        if self._samples_done >= self.max_samples:
            self._apply(self.bo.best_index())
            self.frozen = True
            if self._log:
                self._log.write_line("# converged: fusion=%d cycle=%.3f"
                                     % (self.fusion_threshold,
                                        self.cycle_time_ms))
        else:
            self._apply(self.bo.next_index())
