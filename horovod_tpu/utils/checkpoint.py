"""Rank-0 checkpointing helpers.

The reference deliberately delegates durable checkpointing to the
framework — its examples save on rank 0 only, and elastic mode keeps
*in-memory* state (SURVEY.md §5 "Checkpoint / resume").  This module is
the thin idiomatic equivalent for JAX users: orbax-backed pytree
save/restore that only rank 0 writes, everyone restores, composing
with ``hvd.elastic`` (commit in memory every N steps, checkpoint to
disk every M).
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, "step_%08d" % step)


def save_checkpoint(directory: str, step: int, state: Any,
                    keep: Optional[int] = None):
    """Write ``state`` (any pytree) under ``directory/step_NNNNNNNN``;
    call on every rank — only rank 0 writes (reference examples'
    ``if hvd.rank() == 0: save`` pattern), others return immediately."""
    from ..common import basics
    if basics.is_initialized() and basics.rank() != 0:
        return
    path = _step_dir(directory, step)
    _checkpointer().save(path, state, force=True)
    if keep:
        # prune by recency of WRITE, not by step number: after an
        # elastic rollback a newly saved lower step must survive and
        # the stale higher steps should be the ones to go
        import shutil
        steps = all_steps(directory)
        steps.sort(key=lambda st: os.path.getmtime(_step_dir(directory,
                                                             st)))
        for st in steps[:-keep]:
            shutil.rmtree(_step_dir(directory, st), ignore_errors=True)


def all_steps(directory: str):
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                out.append(int(name[len("step_"):]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, step: Optional[int] = None,
                       item: Any = None) -> Any:
    """Restore the pytree at ``step`` (default: latest).  ``item`` — a
    template pytree for structure/dtype guidance (orbax ``item=``)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError("no checkpoints under %s"
                                    % directory)
    return _checkpointer().restore(_step_dir(directory, step),
                                   item=item)
