"""Persistent autotuned collective-plan cache with fleet-shared warm starts.

Rounds 9-13 built every ingredient of ROADMAP item 1 — the GP/EI
autotuner (``utils/autotune.py`` + ``core/src/parameter_manager.cc``),
per-(op, size_class) path telemetry (``mh_collective_seconds``,
``mh_collective_path_total``) and the r9 flash-block plan registry —
but every job still cold-started from static defaults and one global
``HOROVOD_HIERARCHICAL_ALLREDUCE_THRESHOLD``.  This module closes the
loop:

* **Plan model** — one *plan set* per topology fingerprint
  (``n_procs x local_chips x device_kind``): a per-``(op, size_class)``
  decision table (hier-vs-flat leg + cross-host codec engagement), the
  tuned ``(fusion_threshold, cycle_time)`` operating point, and the r9
  flash-block registry, folded into ONE plane so kernel and collective
  plans live together.
* **Persistence** — a versioned on-disk blob under
  ``HOROVOD_PLAN_CACHE_DIR`` written with the spill-plane atomicity
  conventions (MAGIC + schema version + length + CRC32, same-directory
  temp + ``os.replace``).  Corrupt or version-mismatched blobs are
  skipped LOUDLY and the run falls back to defaults; ``hvd.init()``
  loads the blob so a rerun cold-starts at the tuned operating point.
* **Fleet sharing** — on worlds bootstrapped through the rendezvous KV,
  rank 0 publishes its loaded plan at init and every other member
  adopts the published copy, so late joiners and elastically respawned
  workers start from the pod's best-known plan instead of re-tuning —
  and so every member routes IDENTICALLY (divergent per-class routing
  would diverge the negotiated XLA programs).  Without a KV, the cache
  directory must be shared storage (like ``HOROVOD_STATE_SPILL_DIR``)
  or hold identical content on every host.
* **Tuning** — :func:`tune_collective_plans` is the SPMD sweep
  (``autotune_flash_blocks``'s convention: every member calls it with
  identical arguments): per class, the GP/EI :class:`~.autotune.PlanTuner`
  proposes candidate plans, candidates are scored from the live
  ``mh_collective_seconds{op,size_class}`` telemetry the r11 metrics
  plane records, and scores are cross-rank averaged before every
  proposal/argmax so all members pin the same winner.

Env precedence matches the r9 flash-block convention: explicit gate
envs (``HOROVOD_HIERARCHICAL_ALLREDUCE`` on/off or an explicit
``HOROVOD_HIERARCHICAL_ALLREDUCE_THRESHOLD``) win over any plan AND
suppress pinning; explicit ``HOROVOD_FUSION_THRESHOLD`` /
``HOROVOD_CYCLE_TIME`` suppress the tuned-point warm start the same
way.
"""

from __future__ import annotations

import binascii
import json
import logging
import os
import re
import struct
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import metrics

LOG = logging.getLogger("horovod_tpu.plancache")

MAGIC = b"HVDPLAN1\n"
SCHEMA_VERSION = 1
_HEADER = struct.Struct("!IQI")  # schema_version, payload_len, crc32
_SUFFIX = ".plan"

# Fleet-shared KV key per topology fingerprint; the schema version is
# part of the key so a mixed-version fleet can never adopt a blob its
# decoder does not understand.
_KV_KEY = "plan/v%d/%s"


class PlanCacheInvalid(ValueError):
    """A plan blob failed validation (bad magic, torn payload, CRC
    mismatch, or schema-version mismatch)."""


def topology_fingerprint(n_procs: int, local_size: int,
                         device_kind: str) -> str:
    """Cache key for one payload-plane topology: plans tuned for a
    2-host x 4-chip v5e world must never warm-start an 8-host v4 one."""
    kind = re.sub(r"[^A-Za-z0-9]+", "_",
                  str(device_kind or "unknown")).strip("_")
    return "p%d-l%d-%s" % (int(n_procs), int(local_size),
                           kind or "unknown")


def empty_plan(fingerprint: str) -> dict:
    return {
        "schema": SCHEMA_VERSION,
        "fingerprint": fingerprint,
        # {"fusion_threshold": int, "cycle_time_ms": float,
        #  "converged": bool} once a tuner produced one.
        "tuned": None,
        # op -> {size_class(str) -> {"path": "hier"|"flat",
        #                            "codec": "none"|codec name}}
        "collectives": {},
        # "SEQxDPAD" -> [block_q, block_k] (the r9 flash registry,
        # folded into the same plane).
        "flash_blocks": {},
    }


def _is_plan(obj) -> bool:
    return (isinstance(obj, dict) and obj.get("fingerprint")
            and isinstance(obj.get("collectives", {}), dict)
            and isinstance(obj.get("flash_blocks", {}), dict))


def plan_has_content(plan: Optional[dict]) -> bool:
    return bool(plan) and bool(plan.get("tuned")
                               or plan.get("collectives")
                               or plan.get("flash_blocks"))


# -- blob codec (spill-plane conventions) -----------------------------------

def encode(plan: dict) -> bytes:
    payload = json.dumps(plan, sort_keys=True).encode()
    return (MAGIC
            + _HEADER.pack(SCHEMA_VERSION, len(payload),
                           binascii.crc32(payload) & 0xFFFFFFFF)
            + payload)


def decode(blob: bytes) -> dict:
    """Validated plan dict or :class:`PlanCacheInvalid` — every header
    field is checked before the payload is trusted, and a schema bump
    invalidates old blobs instead of half-reading them."""
    head_len = len(MAGIC) + _HEADER.size
    if len(blob) < head_len or not blob.startswith(MAGIC):
        raise PlanCacheInvalid("bad magic or truncated header "
                               "(%d bytes)" % len(blob))
    schema, payload_len, crc = _HEADER.unpack(blob[len(MAGIC):head_len])
    if schema != SCHEMA_VERSION:
        raise PlanCacheInvalid(
            "plan schema v%d does not match this build's v%d; "
            "re-tune rather than misread" % (schema, SCHEMA_VERSION))
    payload = blob[head_len:]
    if len(payload) != payload_len:
        raise PlanCacheInvalid(
            "torn payload: header promises %d bytes, blob holds %d"
            % (payload_len, len(payload)))
    if binascii.crc32(payload) & 0xFFFFFFFF != crc:
        raise PlanCacheInvalid("payload CRC mismatch")
    try:
        plan = json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise PlanCacheInvalid("undecodable payload: %s" % exc)
    if not _is_plan(plan):
        raise PlanCacheInvalid("payload is not a plan set")
    return plan


def plan_path(d: str, fingerprint: str) -> str:
    return os.path.join(d, "plan-%s%s" % (fingerprint, _SUFFIX))


def store(plan: dict, d: str) -> Optional[str]:
    """Persist one plan set atomically (same-directory temp +
    ``os.replace``, the spill convention — concurrent writers each
    land a complete blob, last one wins).  Never raises: a full disk
    degrades warm starts, it must not kill shutdown or tuning."""
    path = plan_path(d, plan["fingerprint"])
    try:
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".tmp-plan-", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(encode(plan))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path
    except OSError as exc:
        LOG.warning("plan-cache write to %s failed (%s); continuing "
                    "without a persisted plan", path, exc)
        return None


def load(d: str, fingerprint: str) -> Optional[dict]:
    """The persisted plan for this fingerprint, or None.  Bumps
    ``plan_cache_hits_total`` / ``plan_cache_misses_total``; corrupt or
    version-mismatched blobs are a LOUD miss (warning + defaults), so
    a bad blob can never silently pin wrong plans."""
    path = plan_path(d, fingerprint)
    try:
        with open(path, "rb") as f:
            plan = decode(f.read())
    except FileNotFoundError:
        metrics.counter("plan_cache_misses_total").inc()
        return None
    except (OSError, PlanCacheInvalid) as exc:
        metrics.counter("plan_cache_misses_total").inc()
        metrics.event("plan_cache_invalid", path=path, error=str(exc))
        LOG.warning("ignoring unusable plan cache %s (%s); falling "
                    "back to default plans", path, exc)
        return None
    if plan["fingerprint"] != fingerprint:
        metrics.counter("plan_cache_misses_total").inc()
        LOG.warning("plan cache %s claims fingerprint %s, expected %s; "
                    "falling back to default plans", path,
                    plan["fingerprint"], fingerprint)
        return None
    metrics.counter("plan_cache_hits_total").inc()
    return plan


# -- fleet sharing over the rendezvous KV -----------------------------------

def publish_kv(client, plan: dict):
    """Publish one plan set through the rendezvous KV (rank 0 at init,
    and again after a tuning sweep pins new winners) so late joiners
    and respawned workers adopt the pod's best-known plan.  Best
    effort: a dead KV degrades sharing, never the run."""
    try:
        client.put_json(_KV_KEY % (SCHEMA_VERSION, plan["fingerprint"]),
                        plan)
    except Exception as exc:  # noqa: BLE001 - warm starts are optional
        LOG.warning("plan KV publish failed (%s); members fall back to "
                    "their local caches", exc)


def adopt_kv(client, fingerprint: str,
             timeout: float = 60.0) -> Optional[dict]:
    """Block for rank 0's published plan (it publishes before its first
    collective, like the address table) and return it — adopting the
    SAME plan on every member is what keeps per-class routing
    SPMD-identical.  Returns None (loudly) on timeout or a torn
    record: the member then routes by defaults, matching what rank 0
    publishes when it has no plan."""
    try:
        raw = client.get_blocking(
            _KV_KEY % (SCHEMA_VERSION, fingerprint), timeout=timeout)
        plan = json.loads(raw)
        if not _is_plan(plan) or plan["fingerprint"] != fingerprint:
            raise ValueError("published blob is not a plan for %s"
                             % fingerprint)
        return plan
    except Exception as exc:  # noqa: BLE001 - degrade to defaults
        LOG.warning("plan KV adopt for %s failed (%s); using default "
                    "plans", fingerprint, exc)
        return None


# -- per-(op, size_class) routing controller --------------------------------

class PlanController:
    """Deterministic per-``(op, size_class)`` routing decisions for one
    topology fingerprint.

    Precedence per class: env pins (explicit hier mode/threshold —
    suppress everything, the r9 convention) > probe override (the
    tuning sweep forcing a candidate) > plans pinned this run > the
    loaded cache/KV plan > the default byte-threshold gate.  Every
    resolution path is a pure function of negotiated values and
    plan state that is identical on every member by construction
    (shared cache blob or KV adoption), so all members compile the
    same collective programs.
    """

    def __init__(self, fingerprint: str, plan: Optional[dict],
                 source: Optional[str], codec_name: str,
                 hier_available: bool, env_pinned: bool):
        self._lock = threading.Lock()
        self.fingerprint = fingerprint
        self.source = source or "cache"
        self.codec_name = (codec_name or "none")
        self.hier_available = bool(hier_available)
        self.env_pinned = bool(env_pinned)
        self._cached: Dict[Tuple[str, str], dict] = {}
        for op, classes in (plan or {}).get("collectives", {}).items():
            for cls, entry in classes.items():
                if isinstance(entry, dict) and "path" in entry:
                    self._cached[(op, str(cls))] = {
                        "path": entry["path"],
                        "codec": entry.get("codec", "none")}
        self._pinned: Dict[Tuple[str, str], dict] = {}
        self._seen: Dict[Tuple[str, str], dict] = {}
        self._counted: set = set()
        self._forced: Optional[dict] = None
        self._last_cls: Dict[str, str] = {}
        # Resolved-route memo for the dispatch hot path: (op, cls,
        # default_hier) -> (hier, codec_on).  default_hier is part of
        # the key because an unplanned class falls back to the byte
        # gate, and a non-pow2 threshold can split one pow2 class.
        # Invalidated by pin(); force() bypasses it entirely.
        self._memo: Dict[Tuple[str, str, bool], Tuple[bool, bool]] = {}

    def route(self, op: str, cls: str,
              default_hier: bool) -> Tuple[bool, bool]:
        """(use_hier, engage_codec) for one dispatch.  ``default_hier``
        is the global gate's answer; ``engage_codec`` True leaves the
        codec decision to the dtype/op-aware ``_wire_codec`` check."""
        if self._forced is None:
            # Lock-free fast path: per-(op, cls) resolution is
            # deterministic once counted, so repeat dispatches skip
            # the lock and the bookkeeping churn entirely.
            hit = self._memo.get((op, cls, bool(default_hier)))
            if hit is not None:
                return hit
        with self._lock:
            self._last_cls[op] = cls
            if self._forced is not None:
                e = self._forced
                return (e.get("path") == "hier" and self.hier_available,
                        e.get("codec", "none") not in ("", "none"))
            entry = None
            source = "default"
            if not self.env_pinned:
                entry = self._pinned.get((op, cls))
                if entry is not None:
                    source = "tuned"
                else:
                    entry = self._cached.get((op, cls))
                    if entry is not None:
                        source = self.source
            if entry is None:
                hier = bool(default_hier)
                codec_on = True
                codec = (self.codec_name
                         if hier and self.codec_name != "none"
                         else "none")
            else:
                hier = (entry.get("path") == "hier"
                        and self.hier_available)
                codec = entry.get("codec", "none")
                codec_on = (codec not in ("", "none")
                            and codec == self.codec_name)
            key = (op, cls)
            if (key, source) not in self._counted:
                self._counted.add((key, source))
                metrics.counter("plan_apply_total", source=source).inc()
            self._seen[key] = {"path": "hier" if hier else "flat",
                               "codec": codec if hier else "none",
                               "source": source}
            self._memo[(op, cls, bool(default_hier))] = (hier, codec_on)
            return hier, codec_on

    def force(self, entry: Optional[dict]):
        """Probe override: route EVERY class by ``entry`` until cleared
        (the tuning sweep brackets its timed collectives with this; all
        members force the same candidate at the same point, so the
        override is SPMD-consistent)."""
        with self._lock:
            self._forced = dict(entry) if entry is not None else None

    def last_class(self, op: str) -> Optional[str]:
        """The size class the newest ``route()`` call for ``op``
        resolved — how the sweep learns which class its fixed-size
        probe payload actually lands in (gate bytes are op-specific)."""
        with self._lock:
            return self._last_cls.get(op)

    def invalidate(self, op: str, cls: str) -> bool:
        """Drop one class's plan entry (the staleness verdict's
        actuation): cached and pinned entries both go, the route memo
        clears, and the class's counted/seen marks reset so the next
        dispatch re-resolves from scratch (by the default gate, counted
        as a fresh ``plan_apply_total{source="default"}`` — provenance
        stays honest about the fallback).  Returns whether any entry
        was actually dropped.  SPMD contract: called on every member at
        the same point (``check_plan_staleness`` routes the verdict
        through the rendezvous KV), never from rank-local judgement."""
        key = (op, str(cls))
        with self._lock:
            had = self._cached.pop(key, None) is not None
            had = (self._pinned.pop(key, None) is not None) or had
            self._seen.pop(key, None)
            self._counted = {k for k in self._counted if k[0] != key}
            self._memo.clear()  # the drop changes future resolutions
        if had:
            # A stale plan verdict also invalidates any frozen
            # negotiated schedule built over it (SPMD-safe: this runs
            # on every member at the same point, per the contract
            # above).  Lazy import — plancache must not pull the ops
            # package at module load.
            from ..ops import fastpath
            fastpath.thaw_all(
                "staleness",
                detail="plan %s/%s invalidated by staleness verdict"
                % (op, cls))
        return had

    def pin(self, op: str, cls: str, entry: dict) -> bool:
        """Pin a tuned winner for one class; refused (False) when env
        pins suppress planning — an explicit operator A/B must stay
        exactly what was asked for, matching the flash-block rule."""
        if self.env_pinned:
            return False
        with self._lock:
            self._pinned[(op, str(cls))] = dict(entry)
            self._memo.clear()  # the pin changes future resolutions
        return True

    def decisions(self) -> Dict[str, Dict[str, dict]]:
        """The live per-class decision table (bench ``levers.plan``)."""
        with self._lock:
            out: Dict[str, Dict[str, dict]] = {}
            for (op, cls), entry in sorted(self._seen.items()):
                out.setdefault(op, {})[cls] = dict(entry)
            return out

    def export_collectives(self) -> Dict[str, Dict[str, dict]]:
        """Decisions worth persisting: everything routed this run plus
        every pin, path/codec only (sources are runtime provenance)."""
        with self._lock:
            merged = dict(self._seen)
            for key, entry in self._pinned.items():
                merged[key] = {"path": entry.get("path", "flat"),
                               "codec": entry.get("codec", "none")}
            out: Dict[str, Dict[str, dict]] = {}
            for (op, cls), entry in sorted(merged.items()):
                out.setdefault(op, {})[cls] = {
                    "path": entry.get("path", "flat"),
                    "codec": entry.get("codec", "none")}
            return out


# -- process-wide plane state -----------------------------------------------

class _PlanPlane:
    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = False
        self.tune_enabled = False
        self.dir: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.loaded: Optional[dict] = None
        self.source: Optional[str] = None  # "cache" | "kv"
        self.controller: Optional[PlanController] = None
        self.tuned_runtime: Optional[dict] = None
        self.kv = None  # live RendezvousClient for republish, or None
        self.rank: Optional[int] = None
        self.size: Optional[int] = None
        # Staleness-check state (lazy: built at the first
        # check_plan_staleness call so the ratio env is read when the
        # check runs, not at plane reset).
        self.staleness = None


_plane = _PlanPlane()


def reset():
    """Drop all plane state (tests, and re-init after shutdown)."""
    global _plane
    _plane = _PlanPlane()
    # The resilience plane rides the same world identity (rank / KV /
    # fingerprint); a plane reset means that identity is gone, so its
    # demotion state and SPMD check sequence must restart with it.
    from ..common import resilience
    resilience.reset()


def world_plane() -> _PlanPlane:
    """The live plan plane: world identity (rank, size, fingerprint),
    the rendezvous KV handle, and the active :class:`PlanController`.
    The data-plane resilience layer (common/resilience.py) reads this
    to publish/adopt SPMD-uniform degraded-route verdicts through the
    same KV record protocol as plan staleness."""
    return _plane


def _env_pins_gate() -> bool:
    """Whether explicit gate envs suppress per-class planning: an
    explicit hier mode (on/off — not the 'auto' default) or an
    explicit threshold means the operator chose the gate."""
    from ..common.config import env_explicit
    v = (os.environ.get("HVD_TPU_HIERARCHICAL_ALLREDUCE")
         or os.environ.get("HOROVOD_HIERARCHICAL_ALLREDUCE") or "")
    explicit_mode = v.strip().lower() not in ("", "auto")
    return explicit_mode or env_explicit(
        "HIERARCHICAL_ALLREDUCE_THRESHOLD")


def _apply_flash(plan: dict):
    """Seed the r9 flash-block registry from the plan (env block
    overrides win and suppress seeding, the flash precedence rule)."""
    if not plan.get("flash_blocks"):
        return
    if (os.environ.get("HVD_TPU_FLASH_BLOCK_Q")
            or os.environ.get("HVD_TPU_FLASH_BLOCK_K")):
        return
    from ..ops import pallas_kernels
    pallas_kernels.seed_tuned_blocks(plan["flash_blocks"])


def _agree_plan(plane, config, mode, n_procs, kv_world,
                local_plan):  # graftlint: spmd-uniform -- rank-0-publish -> blocking-adopt: rank 0's answer (its local blob, or the KV's prior one) is published under the fingerprint key; every other member blocks on that key and REPLACES its local view with the adopted answer or raises (multihost), so all members leave with the identical plan.  A KV-less multihost world drops the local blob entirely (per-host cache files may differ).
    """World agreement on the active plan.

    The local cache blob is a per-host filesystem read — two hosts can
    legitimately hold different blobs (independent disks, one stale
    rerun) — so it must never steer routing directly on a multi-member
    world.  Rank 0's view becomes THE plan by publishing it to the
    rendezvous KV; members adopt that published answer (blocking) or
    fail loudly.  Without a KV to agree through, a multihost world
    gets no plan at all: divergent per-class hier/flat choices compile
    divergent XLA programs — a distributed hang, not a slowdown (the
    r14 bug class).  tcp mode has no routing controller, so it keeps
    its local view (fusion/cycle pacing only, composition is
    negotiated per cycle).
    """
    plan = local_plan
    if kv_world:
        from ..runner.http_client import RendezvousClient
        plane.kv = RendezvousClient(config.rendezvous_addr,
                                    secret=config.secret_key)
        if plane.rank == 0:
            if plan is None:
                # A long-lived KV may still hold the plan the LAST run
                # republished at shutdown (the KV-only persistence
                # path, and dir-miss reruns against a shared
                # rendezvous): adopt it instead of clobbering it with
                # an empty answer — cross-run KV warm starts depend on
                # it, and it keeps rank 0's publish idempotent, so a
                # member racing the overwrite still reads identical
                # content.
                try:
                    prior = plane.kv.get_json(
                        _KV_KEY % (SCHEMA_VERSION, plane.fingerprint))
                except Exception:  # noqa: BLE001 - optional warm start
                    prior = None
                if (_is_plan(prior)
                        and prior["fingerprint"] == plane.fingerprint
                        and plan_has_content(prior)):
                    plan = prior
                    plane.source = "kv"
            # Publish even an empty plan: members block on this key,
            # and "no plan" is an answer they must agree on.
            publish_kv(plane.kv,
                       plan if plan is not None
                       else empty_plan(plane.fingerprint))
        else:
            adopted = adopt_kv(plane.kv, plane.fingerprint)
            if adopted is None and mode == "multihost":
                # A member that cannot learn rank 0's answer must NOT
                # guess: divergent per-class hier/flat choices diverge
                # the negotiated XLA programs across the world (a hang,
                # not a slowdown).  tcp mode has no routing controller,
                # so it degrades to its local view instead.
                raise RuntimeError(
                    "collective-plan KV adoption failed on a multihost "
                    "world: members must route by rank 0's published "
                    "plan or not at all; fix the rendezvous KV or "
                    "disable the plane with HOROVOD_PLAN_CACHE=0")
            if adopted is not None:
                # The adopted answer REPLACES any local view, even
                # when empty: agreeing on "no plan" beats routing by a
                # local blob rank 0 never saw.
                plan = adopted
                plane.source = ("kv" if plan_has_content(adopted)
                                else None)
    elif mode == "multihost" and n_procs > 1 and plan is not None:
        # No KV to agree through: members CANNOT verify their local
        # blobs match, and applying them anyway is precisely the
        # divergent-routing hang spmd-uniform exists to ban.  Drop the
        # blob (the run degrades to threshold routing and static
        # fusion defaults, still identical everywhere) and say why.
        LOG.warning(
            "plan cache: multihost world with no rendezvous KV — "
            "dropping the local plan blob (%s); per-host cache files "
            "cannot be proven identical, and divergent routing hangs "
            "the world.  Set HOROVOD_RENDEZVOUS_ADDR to share plans.",
            plane.dir)
        plan = None
        plane.source = None
    return plan


def bootstrap(config, topology, mode: str) -> Optional[dict]:
    """Load-and-apply at ``hvd.init()``: resolve the topology
    fingerprint, load the local cache (rank 0) or adopt rank 0's
    KV-published copy (other members — identical routing everywhere),
    warm-start the fusion/cycle tuner, seed the flash registry, and
    install the per-class routing controller (multihost mode).
    Returns the active plan (may be empty) or None when disabled."""
    plane = _plane
    plane.rank = topology.rank if topology is not None else None
    plane.size = topology.size if topology is not None else 1
    plane.enabled = bool(getattr(config, "plan_cache", True))
    plane.tune_enabled = (config.plan_autotune
                          if getattr(config, "plan_autotune", None)
                          is not None else bool(config.autotune))
    plane.dir = getattr(config, "plan_cache_dir", None)
    if not plane.enabled:
        return None
    n_procs = topology.size if topology is not None else 1
    # KV-only operation (ephemeral-disk pods): with no cache dir the
    # rendezvous KV still carries fleet sharing — rank 0 republishes
    # its live-tuned plan at shutdown, so respawned workers and the
    # next KV-bootstrapped run adopt it.  With neither dir nor KV
    # there is nothing to load or share: the plane is inert.
    kv_world = (mode in ("tcp", "multihost") and config.rendezvous_addr
                and n_procs > 1)
    if not plane.dir and not kv_world:
        plane.enabled = False
        return None
    local = 1
    kind = "host"
    if mode in ("inprocess", "multihost"):
        try:
            import jax
            devs = jax.local_devices()
            kind = getattr(devs[0], "device_kind", devs[0].platform)
            if mode == "multihost":
                local = len(devs)
        except Exception:  # noqa: BLE001 - fingerprint must not kill init
            pass
    plane.fingerprint = topology_fingerprint(n_procs, local, kind)

    local_plan = (load(plane.dir, plane.fingerprint)
                  if plane.dir else None)
    plane.source = "cache" if local_plan is not None else None
    plan = _agree_plan(plane, config, mode, n_procs, kv_world,
                       local_plan)
    plane.loaded = plan
    if plan is None:
        plan = empty_plan(plane.fingerprint)

    # Tuned (fusion, cycle) warm start: the cached operating point wins
    # over the static defaults but never over explicit operator envs.
    from ..common.config import env_explicit
    tuned = plan.get("tuned")
    if (tuned and not env_explicit("FUSION_THRESHOLD")
            and not env_explicit("CYCLE_TIME")):
        config.fusion_threshold_bytes = int(tuned["fusion_threshold"])
        config.cycle_time_ms = float(tuned["cycle_time_ms"])
        metrics.counter("plan_apply_total",
                        source=plane.source or "cache").inc()

    _apply_flash(plan)

    if mode == "multihost":
        plane.controller = PlanController(
            plane.fingerprint, plan, plane.source,
            config.cross_host_compression,
            hier_available=(config.hierarchical_allreduce != "off"),
            env_pinned=_env_pins_gate())
    return plan


def tuned_warm_start() -> Optional[Tuple[int, float, bool]]:
    """The loaded plan's (fusion_threshold, cycle_time_ms, converged)
    for tuner warm starts, or None when there is no plan — or when
    explicit operator envs pin the operating point (env wins and
    suppresses the warm start, the r9 precedence rule)."""
    plane = _plane
    plan = plane.loaded
    if not plane.enabled or not plan or not plan.get("tuned"):
        return None
    from ..common.config import env_explicit
    if env_explicit("FUSION_THRESHOLD") or env_explicit("CYCLE_TIME"):
        return None
    t = plan["tuned"]
    return (int(t["fusion_threshold"]), float(t["cycle_time_ms"]),
            bool(t.get("converged", False)))


def controller_for(n_procs: int, local_size: int,
                   device_kind: str) -> Optional[PlanController]:
    """The installed controller, iff its fingerprint matches this
    mesh's topology (process-set sub-meshes with other shapes must
    route by the default gate — their classes were never tuned)."""
    ctl = _plane.controller
    if ctl is None:
        return None
    fp = topology_fingerprint(n_procs, local_size, device_kind)
    if fp != ctl.fingerprint:
        return None
    # The controller's hier availability is refined by the REAL mesh:
    # a single-local-chip world can never route hier whatever the
    # plan says (deterministic on every member — k is a world
    # property).
    if local_size <= 1:
        ctl.hier_available = False
    return ctl


def note_tuned(fusion_threshold: int, cycle_time_ms: float,
               converged: bool):
    """Stage a live-tuned (fusion, cycle) operating point for
    persistence (the in-process engine calls this when its GP tuner
    converges; the native core's point is read at shutdown)."""
    plane = _plane
    with plane.lock:
        first = plane.tuned_runtime is None
        plane.tuned_runtime = {
            "fusion_threshold": int(fusion_threshold),
            "cycle_time_ms": float(cycle_time_ms),
            "converged": bool(converged)}
    if first:
        metrics.counter("plan_apply_total", source="tuned").inc()


def _merged_plan() -> Optional[dict]:
    plane = _plane
    if not plane.enabled or plane.fingerprint is None:
        return None
    plan = (dict(plane.loaded) if plane.loaded is not None
            else empty_plan(plane.fingerprint))
    plan["schema"] = SCHEMA_VERSION
    plan["fingerprint"] = plane.fingerprint
    with plane.lock:
        if plane.tuned_runtime is not None:
            plan["tuned"] = dict(plane.tuned_runtime)
    if plane.controller is not None:
        merged = dict(plan.get("collectives", {}))
        for op, classes in plane.controller.export_collectives().items():
            dst = dict(merged.get(op, {}))
            dst.update(classes)
            merged[op] = dst
        plan["collectives"] = merged
    try:
        from ..ops import pallas_kernels
        blocks = dict(plan.get("flash_blocks", {}))
        blocks.update(pallas_kernels.export_tuned_blocks())
        plan["flash_blocks"] = blocks
    except Exception:  # noqa: BLE001 - flash plane is optional here
        pass
    return plan


def persist(publish: bool = True) -> Optional[str]:
    """Write the merged plan to the cache (rank 0 or rankless worlds;
    every writer lands an atomic complete blob anyway) and republish
    it to the KV so live members' successors warm-start from it."""
    plane = _plane
    plan = _merged_plan()
    if plan is None or not plan_has_content(plan):
        return None
    path = None
    if plane.rank in (None, 0) and plane.dir:
        path = store(plan, plane.dir)
    if publish and plane.kv is not None and plane.rank in (None, 0):
        publish_kv(plane.kv, plan)  # graftlint: spmd-uniform -- rank-0-only republish: this blob is the NEXT run's adoption point (never read back into this run's routing); members hit the rank guard above
    return path


def finalize(tcp_core=None, engine=None):
    """Shutdown hook: harvest the live tuners' operating points (the
    native core's autotune state, or the in-process ParameterManager)
    and persist the merged plan.  Never raises into shutdown."""
    plane = _plane
    if not plane.enabled:
        return
    try:
        # samples > 0 distinguishes "tuned THIS run" from a frozen
        # warm start replaying the cached point: only live tuning is
        # (re)staged, so plan_apply_total{source="tuned"} stays honest
        # provenance and a pure warm-start run re-persists the loaded
        # plan unchanged through the merge.
        pm = getattr(engine, "parameter_manager", None)
        if pm is not None and pm.samples_done > 0:
            note_tuned(pm.fusion_threshold, pm.cycle_time_ms, pm.frozen)
        if tcp_core is not None:
            st = tcp_core.autotune_state()
            if st is not None and st["samples"] > 0:
                note_tuned(st["fusion_threshold"], st["cycle_time_ms"],
                           bool(st["converged"]))
        persist()
    except Exception as exc:  # noqa: BLE001 - shutdown must not fail
        LOG.warning("plan-cache finalize failed: %s", exc)


def describe() -> dict:
    """Attribution block for ``bench.py``'s ``levers.plan``: cache
    path, hit/miss counters, schema version, plan source and the
    per-class decision table."""
    plane = _plane
    out = {
        "enabled": plane.enabled,
        "schema": SCHEMA_VERSION,
        "dir": plane.dir,
        "fingerprint": plane.fingerprint,
        "source": plane.source,
        "hits": metrics.series_sum("plan_cache_hits_total"),
        "misses": metrics.series_sum("plan_cache_misses_total"),
        "apply": {
            src: metrics.series_sum("plan_apply_total", source=src)
            for src in ("cache", "kv", "tuned", "default")},
        "tune_samples": metrics.series_sum("plan_tune_samples_total"),
    }
    if plane.controller is not None:
        out["decisions"] = plane.controller.decisions()
    with plane.lock:
        if plane.tuned_runtime is not None:
            out["tuned"] = dict(plane.tuned_runtime)
    return out


# -- plan staleness: observed-vs-expected drift, SPMD-uniform ---------------

# One record per fingerprint on the rendezvous KV: rank 0 overwrites it
# every check with {"seq": N, "stale": [trip history]}; members gate on
# seq and apply trips by their ``apply_at`` seq — never on local
# judgement.
_STALE_KEY = "plan/stale/v%d/%s"


class _StalenessState:
    def __init__(self):
        from ..common import skew
        self.seq = 0                 # checks this process has run
        self.tracker = skew.ClassLatencyTracker()  # rank 0 only
        self.entries: List[dict] = []  # rank 0's trip history
        self.applied = 0             # trips applied locally
        self.rearmed: List[Tuple[str, str]] = []  # awaiting re-tune
        self.warned_no_kv = False


def _staleness_state() -> _StalenessState:
    plane = _plane
    with plane.lock:
        if plane.staleness is None:
            plane.staleness = _StalenessState()
        return plane.staleness


def retune_pending() -> List[Tuple[str, str]]:
    """Classes whose cached plan entry went stale and now await
    re-tuning — appended exactly once per trip by
    :func:`check_plan_staleness`, consumed by the caller that re-runs
    :func:`tune_collective_plans` for them (every member sees the
    identical list: trips only ever arrive through the KV verdict)."""
    st = _plane.staleness
    return list(st.rearmed) if st is not None else []


def consume_retune() -> List[Tuple[str, str]]:
    """Pop the pending re-tune classes (call right before sweeping
    them, on every member — the SPMD calling contract)."""
    st = _plane.staleness
    if st is None:
        return []
    out, st.rearmed = list(st.rearmed), []
    return out


def _apply_stale(plane, entry: dict):
    op, cls = entry["op"], entry["size_class"]
    if plane.controller is not None:
        plane.controller.invalidate(op, cls)
    metrics.counter("plan_staleness_total", op=op,
                    size_class=cls).inc()
    metrics.event("plan_stale", scope="member", rank=plane.rank,
                  **entry)
    st = plane.staleness
    st.rearmed.append((op, cls))
    LOG.warning(
        "plan entry (%s, %s) invalidated as STALE (observed %.6fs vs "
        "baseline %.6fs, %.1fx drift): routing falls back to the "
        "default gate and the class is re-armed for tuning",
        op, cls, entry.get("observed_s", 0.0),
        entry.get("baseline_s", 0.0), entry.get("ratio", 0.0))


def check_plan_staleness(timeout: float = 60.0) -> Optional[dict]:  # graftlint: spmd-uniform -- rank-0-decide -> KV-adopt: only rank 0's ClassLatencyTracker ever produces a trip; the trip history is published under the fingerprint key with an apply_at seq, every member blocks for a record covering ITS OWN seq and applies exactly the trips with apply_at <= that seq, so all members invalidate the same classes at the same check index (in between, routing is untouched everywhere).  KV-less multi-member worlds return None before any state mutates.
    """Observed-vs-expected plan drift check — the decide half of the
    staleness loop.  EVERY member calls this at the same point in its
    step sequence (the ``tune_collective_plans`` SPMD contract; pick a
    cadence you can afford — each check is one KV round-trip).

    Rank 0 feeds its live ``mh_collective_seconds`` per-class totals
    into a :class:`~horovod_tpu.common.skew.ClassLatencyTracker`:
    a class whose window mean drifts past
    ``HOROVOD_PLAN_STALENESS_RATIO`` x its recorded baseline (the
    latency the active plan delivered when tracking began) is STALE —
    one class per check, worst first.  The verdict is routed through
    the rendezvous KV (rank 0 publishes its trip history stamped with
    the check seq; members block for a record covering their own seq)
    so the invalidation lands at the SAME check index on every member
    — per-class routing must never diverge (the r14 hang class).  On
    a trip every member drops the class from its controller
    (:meth:`PlanController.invalidate`), bumps
    ``plan_staleness_total{op,size_class}``, journals ``plan_stale``,
    and re-arms the class for tuning exactly once
    (:func:`retune_pending`).

    Returns the trip applied this check (or None).  Multi-member
    worlds without a rendezvous KV cannot agree and observe nothing
    (warned once); a member that cannot reach rank 0's record raises
    rather than guess."""
    plane = _plane
    if not plane.enabled or plane.fingerprint is None:
        return None
    from ..common import skew
    if skew.plan_staleness_ratio() <= 0:
        return None
    st = _staleness_state()
    multi = (plane.size or 1) > 1
    if multi and plane.kv is None:
        if not st.warned_no_kv:
            st.warned_no_kv = True
            LOG.warning(
                "plan staleness check skipped: multi-member world "
                "with no rendezvous KV to agree through (set "
                "HOROVOD_RENDEZVOUS_ADDR) — rank-local invalidation "
                "would diverge per-class routing")
        return None
    st.seq += 1
    key = _STALE_KEY % (SCHEMA_VERSION, plane.fingerprint)
    if plane.rank in (None, 0):
        verdict = st.tracker.update(
            skew._class_totals(metrics.snapshot()))
        if verdict is not None:
            st.entries.append(dict(verdict, apply_at=st.seq))
        if multi:
            plane.kv.put_json(key, {"seq": st.seq,
                                    "stale": st.entries})
        visible = st.entries
    else:
        deadline = time.monotonic() + timeout
        rec = None
        while True:
            rec = plane.kv.get_json(key)
            if isinstance(rec, dict) and rec.get("seq", 0) >= st.seq:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "plan staleness check: rank 0 never published "
                    "check #%d for %s — members must adopt rank 0's "
                    "verdict or not at all (the divergent-routing "
                    "hang class)" % (st.seq, plane.fingerprint))
            time.sleep(0.05)
        # Only trips rank 0 decided AT OR BEFORE this member's own
        # check index apply now; later ones apply at their own index.
        visible = [e for e in rec.get("stale", ())
                   if e.get("apply_at", 0) <= st.seq]
    fresh = visible[st.applied:]
    for entry in fresh:
        _apply_stale(plane, entry)
    st.applied = len(visible)
    return dict(fresh[-1]) if fresh else None


# -- the tuning sweep -------------------------------------------------------

def _hist_totals(name: str, **labels) -> Tuple[float, float]:
    """(sum_seconds, count) over every series of one histogram family
    whose labels contain ``labels`` — the live-telemetry read the
    sweep scores from."""
    fam = metrics.snapshot().get(name)
    total, count = 0.0, 0.0
    if not fam:
        return total, count
    for row in fam.get("series", []):
        row_labels = row.get("labels", {})
        if all(row_labels.get(k) == str(v) for k, v in labels.items()):
            total += float(row.get("sum", 0.0))
            count += float(row.get("count", 0.0))
    return total, count


def _probe_payload(op: str, nbytes: int, size: int):
    import numpy as np
    n = max(int(nbytes) // 4, size)
    if op == "alltoall":
        n = -(-n // size) * size  # uniform splits need dim0 % size == 0
    # Rank-identical payloads: the probe measures movement, and
    # identical inputs keep every reduce numerically boring.
    return np.random.RandomState(0).randn(n).astype(np.float32)


def _op_runner(op: str, hvd):
    if op == "allreduce":
        return lambda p: hvd.allreduce(p, op=hvd.Sum,
                                       name="plan.probe.allreduce")
    if op == "allgather":
        return lambda p: hvd.allgather(p, name="plan.probe.allgather")
    if op == "broadcast":
        return lambda p: hvd.broadcast(p, root_rank=0,
                                       name="plan.probe.broadcast")
    if op == "reducescatter":
        return lambda p: hvd.reducescatter(p,
                                           name="plan.probe.reducescatter")
    if op == "alltoall":
        return lambda p: hvd.alltoall(p, name="plan.probe.alltoall")
    raise ValueError("unknown probe op %r" % op)


def tune_collective_plans(sizes_bytes=(1 << 20,), ops=("allreduce",),
                          iters: int = 3, samples_per_class: int = 0,
                          pin: bool = True, persist_after: bool = True):
    """SPMD per-(op, size_class) plan sweep over the widened search
    space: hier-vs-flat leg x cross-host codec engagement.

    EVERY member process must call this with identical arguments (the
    ``autotune_flash_blocks`` contract): the sweep forces one candidate
    plan at a time, drives ``iters`` real collectives through the
    public eager API, scores the candidate from the live
    ``mh_collective_seconds{op,size_class}`` dispatch-to-completion
    telemetry (wall-clock fallback when the histogram window is
    racing), cross-rank AVERAGES every score before feeding the GP/EI
    :class:`~.autotune.PlanTuner` — so proposals and the final argmax
    are identical on all members — and pins each class's winner into
    the live routing plan (env gate pins suppress pinning).  Winners
    are persisted and republished so the whole fleet warm-starts.

    Returns ``{(op, size_class): {"best", "pinned", "samples",
    "scores"}}``.
    """
    import numpy as np

    import horovod_tpu as hvd  # lazy: this module is imported by init

    from .autotune import PlanTuner

    plane = _plane
    ctl = plane.controller
    if ctl is None:
        raise RuntimeError(
            "plan tuning needs the collective-plan plane: multihost "
            "mode with HOROVOD_PLAN_CACHE_DIR set (and HOROVOD_PLAN_CACHE "
            "not disabled)")
    if not plane.tune_enabled:
        raise RuntimeError(
            "plan tuning is disabled: set HOROVOD_PLAN_AUTOTUNE=1 "
            "(or HOROVOD_AUTOTUNE=1) to enable the per-class sweep")
    size = hvd.size()
    candidates: List[dict] = [{"path": "flat", "codec": "none"}]
    coords = [(0.0, 0.0)]
    if ctl.hier_available:
        candidates.append({"path": "hier", "codec": "none"})
        coords.append((1.0, 0.0))
        if ctl.codec_name != "none":
            candidates.append({"path": "hier", "codec": ctl.codec_name})
            coords.append((1.0, 1.0))

    def avg_scalar(x: float) -> float:  # graftlint: spmd-uniform -- cross-rank Average over the collective plane: every member contributes its local score and receives the identical mean, so GP proposals and the final argmax match on all members
        # Cross-rank mean via the regular collective plane: identical
        # inputs ordering -> bit-identical result on every member.
        v = np.asarray([x], np.float32)
        return float(np.asarray(hvd.allreduce(
            v, op=hvd.Average, name="plan.probe.score")).reshape(-1)[0])

    results = {}
    for op in ops:
        runner = _op_runner(op, hvd)
        for nbytes in sizes_bytes:
            payload = _probe_payload(op, int(nbytes), size)
            tuner = PlanTuner(coords,
                              max_samples=samples_per_class * len(coords)
                              or None)
            cls = None
            while not tuner.converged:
                idx = tuner.propose()
                ctl.force(candidates[idx])
                try:
                    s0, c0 = _hist_totals("mh_collective_seconds", op=op)
                    t0 = time.perf_counter()
                    for _ in range(max(int(iters), 1)):
                        runner(payload)
                    wall = time.perf_counter() - t0
                    s1, c1 = _hist_totals("mh_collective_seconds", op=op)
                finally:
                    ctl.force(None)
                cls = ctl.last_class(op) or "0"
                # Live-telemetry score (dispatch->completion from the
                # r11 histogram); the wall clock covers the race where
                # the last completion's observe lands after the read.
                secs = (s1 - s0) if (c1 - c0) >= iters else wall
                score = float(int(nbytes) * max(int(iters), 1)
                              / max(secs, 1e-9))
                tuner.record(idx, avg_scalar(score))
                metrics.counter("plan_tune_samples_total", op=op,
                                size_class=cls).inc()
            best_idx = tuner.best()
            entry = dict(candidates[best_idx])
            pinned = bool(pin) and ctl.pin(op, cls, entry)
            results[(op, cls)] = {
                "best": entry, "pinned": pinned,
                "samples": tuner.samples,
                "scores": tuner.mean_scores(),
            }
            if not pinned and pin:
                LOG.warning(
                    "plan pin for (%s, %s) suppressed: explicit "
                    "hierarchical gate env wins over the tuner", op, cls)
    if persist_after:
        persist()
    return results
