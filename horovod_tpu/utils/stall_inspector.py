"""Stall inspector: the runtime deadlock/mismatch diagnosis tool.

Equivalent of the reference's ``horovod/common/stall_inspector.cc``: if a
collective has been submitted but not completed for longer than the warning
threshold (``HOROVOD_STALL_CHECK_TIME_SECONDS``, default 60 s), log which
tensors are stuck — in multi-process mode, also which ranks are missing
them.  Optionally aborts after a shutdown threshold
(``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``, default 0 = warn only;
mirrored by ``core/src/stall_inspector.h`` ``kDefaultShutdownSecs`` —
the two planes must agree on when a stall turns fatal).  In an elastic
world the resulting :class:`StallError` does not hard-kill the worker:
``hvd.elastic.run`` routes it through the drain protocol
(committed-then-abort, distinguished exit code, no blacklist churn for
the healthy host that merely observed a peer's death).

This is the most-loved debugging feature of the reference (it turns a hang
into an actionable message like "ranks 1,3 have not submitted tensor X"),
so it is kept as a first-class component.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..common import metrics

LOG = logging.getLogger("horovod_tpu")


class StallError(RuntimeError):
    """Raised when a stall exceeds the shutdown threshold."""


class StallInspector:
    def __init__(self, warning_secs: float = 60.0,
                 shutdown_secs: float = 0.0,
                 enabled: bool = True,
                 reporter: Optional[Callable[[str], None]] = None):
        self.warning_secs = warning_secs
        self.shutdown_secs = shutdown_secs
        self.enabled = enabled and warning_secs > 0
        self._reporter = reporter or (lambda msg: LOG.warning(msg))
        # tensor name -> (enqueue time, optional "who's missing" info)
        self._pending: Dict[str, Tuple[float, Optional[List[int]]]] = {}
        self._warned: Dict[str, float] = {}
        self._last_check = time.monotonic()

    # -- bookkeeping (called by the engine/controller) ---------------------

    def record_enqueue(self, tensor_name: str,
                       missing_ranks: Optional[List[int]] = None):
        self._pending[tensor_name] = (time.monotonic(), missing_ranks)

    def record_update_missing(self, tensor_name: str,
                              missing_ranks: List[int]):
        if tensor_name in self._pending:
            t, _ = self._pending[tensor_name]
            self._pending[tensor_name] = (t, missing_ranks)

    def record_done(self, tensor_name: str):
        self._pending.pop(tensor_name, None)
        self._warned.pop(tensor_name, None)

    def has_outstanding(self) -> bool:
        """Any enqueued-but-unfinished tensors (drives the engine's
        idle-sleep coarsening)."""
        return bool(self._pending)

    # -- checking (called once per background cycle) -----------------------

    def check(self) -> List[str]:
        """Returns names of currently-stalled tensors; emits warnings."""
        if not self.enabled:
            return []
        now = time.monotonic()
        # The reference rate-limits checks to the warning interval itself.
        if now - self._last_check < min(self.warning_secs, 1.0):
            return []
        self._last_check = now
        stalled = []
        for name, (t0, missing) in list(self._pending.items()):
            age = now - t0
            if age < self.warning_secs:
                continue
            stalled.append(name)
            last_warn = self._warned.get(name, 0.0)
            if now - last_warn >= self.warning_secs:
                self._warned[name] = now
                # The r10 stall-abort path must be countable, not just
                # grep-able: every warning is a counter tick and a
                # structured event alongside the log line.
                metrics.counter("stall_detected_total").inc()
                metrics.event("stall", tensor=name, age_secs=round(age, 3),
                              missing_ranks=missing)
                if missing:
                    self._reporter(
                        "Stalled collective: tensor %r has waited %.0f s; "
                        "ranks %s have not submitted it. One or more ranks "
                        "may have died or diverged in their collective call "
                        "order." % (name, age, missing))
                else:
                    self._reporter(
                        "Stalled collective: tensor %r has waited %.0f s "
                        "without completing. Possible causes: a rank died, "
                        "or ranks issued collectives in different orders."
                        % (name, age))
            if self.shutdown_secs > 0 and age >= self.shutdown_secs:
                metrics.event("stall_abort", tensor=name,
                              age_secs=round(age, 3))
                raise StallError(
                    "Collective %r stalled beyond the shutdown threshold "
                    "(%.0f s); aborting." % (name, self.shutdown_secs))
        return stalled
