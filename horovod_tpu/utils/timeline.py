"""Chrome-trace timeline for per-tensor collective lifecycles.

Equivalent of the reference's ``horovod/common/timeline.cc``: every tensor's
journey (NEGOTIATE -> QUEUE -> FUSE -> EXEC -> DONE) is appended to a
``chrome://tracing``-loadable JSON array when a timeline file is configured
(``HOROVOD_TIMELINE=/path.json`` or ``hvd.start_timeline(path)``).
``HOROVOD_TIMELINE_MARK_CYCLES`` adds an instant event per background-loop
cycle, like the reference's cycle markers.

Crash durability: the writer keeps the on-disk array *valid* on a
cadence (``HOROVOD_TIMELINE_FLUSH_SECS``, default 5 s) by writing the
closing ``]`` after the newest record and seeking back over it before
the next one — so a preempted or SIGKILLed worker (the r10 drain path's
force-exit included) leaves a loadable trace instead of a torn JSON
array.  ``shutdown()`` is idempotent and tolerates being called after
an abort already tore the process down around it.

Cross-plane correlation: EXEC events carry the dispatching engine's
monotonic collective-group id in ``args.group`` — the same id the
metrics plane exposes as ``engine_last_group_id`` — so a latency spike
in a scraped histogram can be matched to the exact trace span.

On TPU the XLA/PJRT profiler (xprof) covers device-side detail; this
timeline covers the host-side scheduling story, which is what the
reference's timeline was for.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ..common.envutil import env_float

_TAIL = "\n]\n"


def flush_secs() -> float:
    """Valid-tail cadence (``HOROVOD_TIMELINE_FLUSH_SECS``, default 5 s,
    floor 0 = after every record)."""
    return env_float("HOROVOD_TIMELINE_FLUSH_SECS", 5.0, minimum=0.0)


class Timeline:
    """Thread-safe incremental chrome-trace writer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._path: Optional[str] = None
        self._first = True
        self._start_ts = time.monotonic()
        self._pending_negotiation = {}
        self.mark_cycles = False
        # Byte offset of the provisional closing tail, when one is on
        # disk (the array is valid right now); None = tail not written
        # since the last record.
        self._tail_pos: Optional[int] = None
        self._last_tail = 0.0
        self._flush_secs = 5.0

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, path: Optional[str], mark_cycles: bool = False):
        if not path:
            return
        with self._lock:
            if self._fh is not None:
                return
            self._path = path
            self.mark_cycles = mark_cycles
            # Snapshot the cadence once per trace: the env cannot
            # meaningfully change mid-run, and the emit path must not
            # re-parse it per record.
            self._flush_secs = flush_secs()
            self._fh = open(path, "w")
            self._fh.write("[\n")
            self._first = True
            self._tail_pos = None
            self._last_tail = 0.0

    def active(self) -> bool:
        return self._fh is not None

    def shutdown(self):
        """Close the trace; safe to call twice, and safe after an
        abort/drain already invalidated the handle."""
        with self._lock:
            if self._fh is None:
                return
            try:
                if self._tail_pos is None:
                    self._fh.write(_TAIL)
                self._fh.close()
            except (OSError, ValueError):
                pass  # torn handle on the abort path: best effort
            self._fh = None
            self._tail_pos = None

    # -- low-level emit ----------------------------------------------------

    def _us(self) -> int:
        return int((time.monotonic() - self._start_ts) * 1e6)

    def _emit(self, record: dict):
        with self._lock:
            if self._fh is None:
                return
            try:
                if self._tail_pos is not None:
                    # Retract the provisional closing tail.
                    self._fh.seek(self._tail_pos)
                    self._fh.truncate()
                    self._tail_pos = None
                if not self._first:
                    self._fh.write(",\n")
                self._first = False
                self._fh.write(json.dumps(record))
                self._fh.flush()
                now = time.monotonic()
                if now - self._last_tail >= self._flush_secs:
                    # Leave the array valid: a worker killed between
                    # cadence ticks loses at most the tail records,
                    # never the whole trace.
                    self._last_tail = now
                    self._tail_pos = self._fh.tell()
                    self._fh.write(_TAIL)
                    self._fh.flush()
            except (OSError, ValueError):
                # A torn file handle (disk full, abort mid-teardown)
                # must never take the training loop down with it.
                try:
                    self._fh.close()
                except (OSError, ValueError):
                    pass
                self._fh = None
                self._tail_pos = None

    # -- reference-parity API ---------------------------------------------

    def activity_start(self, tensor_name: str, activity: str, rank: int = 0,
                       args: Optional[dict] = None):
        """Begin a phase for one tensor (``Timeline::ActivityStart``)."""
        record = {"name": activity, "ph": "B", "ts": self._us(),
                  "pid": rank, "tid": tensor_name}
        if args:
            record["args"] = args
        self._emit(record)

    def activity_end(self, tensor_name: str, rank: int = 0):
        """End the innermost phase (``Timeline::ActivityEnd``)."""
        self._emit({"ph": "E", "ts": self._us(),
                    "pid": rank, "tid": tensor_name})

    def activity_start_all(self, tensor_names, activity: str, rank: int = 0,
                           args: Optional[dict] = None):
        for n in tensor_names:
            self.activity_start(n, activity, rank, args)

    def activity_end_all(self, tensor_names, rank: int = 0):
        for n in tensor_names:
            self.activity_end(n, rank)

    def negotiate_start(self, tensor_name: str, op_name: str, rank: int = 0):
        self.activity_start(tensor_name, "NEGOTIATE_" + op_name.upper(), rank)

    def negotiate_end(self, tensor_name: str, rank: int = 0):
        self.activity_end(tensor_name, rank)

    def mark_cycle(self, cycle_index: int, rank: int = 0):
        """Instant event per background-loop cycle (mark-cycles parity)."""
        if self.mark_cycles:
            self._emit({"name": "CYCLE_START", "ph": "i", "ts": self._us(),
                        "pid": rank, "tid": "cycle", "s": "g",
                        "args": {"cycle": cycle_index}})


_global_timeline = Timeline()


def get_timeline() -> Timeline:
    return _global_timeline
