"""Chrome-trace timeline for per-tensor collective lifecycles.

Equivalent of the reference's ``horovod/common/timeline.cc``: every tensor's
journey (NEGOTIATE -> QUEUE -> FUSE -> EXEC -> DONE) is appended to a
``chrome://tracing``-loadable JSON array when a timeline file is configured
(``HOROVOD_TIMELINE=/path.json`` or ``hvd.start_timeline(path)``).
``HOROVOD_TIMELINE_MARK_CYCLES`` adds an instant event per background-loop
cycle, like the reference's cycle markers.

On TPU the XLA/PJRT profiler (xprof) covers device-side detail; this
timeline covers the host-side scheduling story, which is what the
reference's timeline was for.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional


class Timeline:
    """Thread-safe incremental chrome-trace writer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._path: Optional[str] = None
        self._first = True
        self._start_ts = time.monotonic()
        self._pending_negotiation = {}
        self.mark_cycles = False

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, path: Optional[str], mark_cycles: bool = False):
        if not path:
            return
        with self._lock:
            if self._fh is not None:
                return
            self._path = path
            self.mark_cycles = mark_cycles
            self._fh = open(path, "w")
            self._fh.write("[\n")
            self._first = True

    def active(self) -> bool:
        return self._fh is not None

    def shutdown(self):
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write("\n]\n")
                self._fh.close()
            except Exception:
                pass
            self._fh = None

    # -- low-level emit ----------------------------------------------------

    def _us(self) -> int:
        return int((time.monotonic() - self._start_ts) * 1e6)

    def _emit(self, record: dict):
        with self._lock:
            if self._fh is None:
                return
            if not self._first:
                self._fh.write(",\n")
            self._first = False
            self._fh.write(json.dumps(record))
            self._fh.flush()

    # -- reference-parity API ---------------------------------------------

    def activity_start(self, tensor_name: str, activity: str, rank: int = 0):
        """Begin a phase for one tensor (``Timeline::ActivityStart``)."""
        self._emit({"name": activity, "ph": "B", "ts": self._us(),
                    "pid": rank, "tid": tensor_name})

    def activity_end(self, tensor_name: str, rank: int = 0):
        """End the innermost phase (``Timeline::ActivityEnd``)."""
        self._emit({"ph": "E", "ts": self._us(),
                    "pid": rank, "tid": tensor_name})

    def activity_start_all(self, tensor_names, activity: str, rank: int = 0):
        for n in tensor_names:
            self.activity_start(n, activity, rank)

    def activity_end_all(self, tensor_names, rank: int = 0):
        for n in tensor_names:
            self.activity_end(n, rank)

    def negotiate_start(self, tensor_name: str, op_name: str, rank: int = 0):
        self.activity_start(tensor_name, "NEGOTIATE_" + op_name.upper(), rank)

    def negotiate_end(self, tensor_name: str, rank: int = 0):
        self.activity_end(tensor_name, rank)

    def mark_cycle(self, cycle_index: int, rank: int = 0):
        """Instant event per background-loop cycle (mark-cycles parity)."""
        if self.mark_cycles:
            self._emit({"name": "CYCLE_START", "ph": "i", "ts": self._us(),
                        "pid": rank, "tid": "cycle", "s": "g",
                        "args": {"cycle": cycle_index}})


_global_timeline = Timeline()


def get_timeline() -> Timeline:
    return _global_timeline
