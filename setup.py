"""Build driver (reference: Horovod's setup.py + CMakeLists.txt, pared
to this framework's needs): compiles the native coordination core
(``horovod_tpu/core/libhvdtpu_core.so``) at build time via its
Makefile — plain g++/make, no third-party build deps.  The library is
also built lazily on first use (``horovod_tpu.core.client``), so a
source checkout works without installation.
"""

import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


class BuildWithNativeCore(build_py):
    def run(self):
        subprocess.run(["make", "-C", "horovod_tpu/core", "-j", "-s"],
                       check=True)
        super().run()


class BinaryDistribution(Distribution):
    """The shipped .so makes wheels platform-specific; without this the
    wheel would be tagged py3-none-any and break cross-platform."""

    def has_ext_modules(self):
        return True


setup(cmdclass={"build_py": BuildWithNativeCore},
      distclass=BinaryDistribution)
