"""Test world setup: 8 virtual CPU devices.

Mirrors the reference's test strategy (SURVEY.md §4): the cheap real-wire
test backend there is Gloo-on-localhost; ours is JAX CPU with
``--xla_force_host_platform_device_count=8`` — a real 8-"chip" world where
XLA collectives actually execute, no mocks.

Must run before any test imports initialize a JAX backend.  The axon TPU
plugin (when present) pins ``JAX_PLATFORMS=axon`` from sitecustomize, so we
override through jax.config, which wins as long as no backend has been
created yet.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Session tag inherited by every process this suite spawns (directly or
# through the launcher/driver): the orphan reaper only ever touches
# processes carrying it, so unrelated Horovod jobs on the box — or a
# concurrent shard's workers — are never swept.
os.environ["HVD_TPU_TEST_SESSION"] = str(os.getpid())

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def hvd_world():
    """Initialized in-process world over the 8 CPU devices; torn down after."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


# -- orphan reaper ----------------------------------------------------------

def _horovod_orphans():
    """PIDs of orphaned Horovod worker processes spawned by THIS
    session: the session tag (``HVD_TPU_TEST_SESSION=<our pid>``,
    exported above and inherited by every spawned tree) plus a
    ``HOROVOD_*`` world/elastic marker in the environment, AND a dead
    parent (ppid reparented to init / this process).  The tag keeps
    unrelated Horovod jobs and concurrent shards out of the sweep; a
    live parent means some still-running harness owns the process."""
    if not os.path.isdir("/proc"):
        return []
    me = os.getpid()
    session_tag = ("HVD_TPU_TEST_SESSION=%d" % me).encode()
    markers = (b"HOROVOD_RANK=", b"HOROVOD_ELASTIC_DRIVER_ADDR=",
               b"HOROVOD_ELASTIC_SLOT=")
    orphans = []
    for name in os.listdir("/proc"):
        if not name.isdigit():
            continue
        pid = int(name)
        if pid == me:
            continue
        try:
            with open("/proc/%d/environ" % pid, "rb") as f:
                environ = f.read()
            # Exact entry match (split on NUL) so session pid 123
            # never claims session 1234's workers.
            if session_tag not in environ.split(b"\0"):
                continue
            if not any(m in environ for m in markers):
                continue
            with open("/proc/%d/stat" % pid) as f:
                stat = f.read()
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            continue  # exited mid-scan / not ours to read
        if ppid in (1, me):
            orphans.append(pid)
    return orphans


@pytest.fixture(scope="session", autouse=True)
def _reap_orphaned_workers():
    """Session teardown sweep: any elastic/multihost worker process that
    outlived its test is killed (whole process group) and FAILS the
    session loudly — a leaked worker is a failed teardown path, exactly
    the class of bug the fault-injection suite exists to catch."""
    yield
    import signal
    import time as _time
    orphans = _horovod_orphans()
    for pid in orphans:
        try:
            # Never killpg our own group: an orphan that was spawned
            # without start_new_session shares pytest's pgid, and
            # sweeping that group would SIGKILL the session itself.
            if os.getpgid(pid) != os.getpgrp():
                os.killpg(os.getpgid(pid), signal.SIGKILL)
            else:
                os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    if orphans:
        _time.sleep(0.5)
        survivors = set(_horovod_orphans()) & set(orphans)
        raise RuntimeError(
            "orphaned Horovod worker processes survived the suite "
            "(pids %s, killed now%s) — some test's teardown leaked its "
            "world" % (sorted(orphans),
                       "" if not survivors else
                       "; STILL ALIVE: %s" % sorted(survivors)))
