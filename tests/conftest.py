"""Test world setup: 8 virtual CPU devices.

Mirrors the reference's test strategy (SURVEY.md §4): the cheap real-wire
test backend there is Gloo-on-localhost; ours is JAX CPU with
``--xla_force_host_platform_device_count=8`` — a real 8-"chip" world where
XLA collectives actually execute, no mocks.

Must run before any test imports initialize a JAX backend.  The axon TPU
plugin (when present) pins ``JAX_PLATFORMS=axon`` from sitecustomize, so we
override through jax.config, which wins as long as no backend has been
created yet.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def hvd_world():
    """Initialized in-process world over the 8 CPU devices; torn down after."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()
