#include "gadget.h"

void Gadget::Set(int v) {
  std::lock_guard<std::mutex> lk(mu_);
  value_ = v;  // clean: under the lock
  Bump();      // clean: mu_ held for the REQUIRES callee
}

void Gadget::Bump() {
  value_ += 1;  // clean: REQUIRES(mu_) — caller holds the lock
}

int Gadget::Peek() const {
  return value_;  // graftlint: disable=cpp-guarded-by issue=ISSUE-10 -- racy monitoring hint only; a torn read is harmless here
}
