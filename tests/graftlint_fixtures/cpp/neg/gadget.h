// Negative fixture: every annotated access is under a matching lock
// scope, REQUIRES-covered, or carries a cited suppression.  Must lint
// clean.
#pragma once

#include <mutex>

class Gadget {
 public:
  void Set(int v) EXCLUDES(mu_);
  int Peek() const EXCLUDES(mu_);

 private:
  void Bump() REQUIRES(mu_);

  mutable std::mutex mu_;
  int value_ GUARDED_BY(mu_) = 0;
};
