#include "tuner.h"

void ParamTuner::Configure(int v) {
  // The digit separator must not open a char literal in the stripper
  // (it would blank everything below, hiding both findings).
  const int kScale = 1'000;
  value_ = v * kScale;  // EXPECT cpp-guarded-by: no lock, not REQUIRES
  Apply(v);             // EXPECT cpp-requires: Apply needs mu_ held
}

void ParamTuner::Flush() {
  std::lock_guard<std::mutex> a(mu_);
  std::lock_guard<std::mutex> b(io_mu_);
  Publish();  // EXPECT cpp-excludes via the SECOND stacked annotation
}

void ParamTuner::Publish() {
  value_ = 0;  // clean: REQUIRES(mu_)
}

void ParamTuner::Reset() {
  Publish();  // EXPECT cpp-requires: the stacked declaration keeps
}             // its REQUIRES(mu_) alongside the EXCLUDES(io_mu_)

int ParamTuner::Get() const {
  std::lock_guard<std::mutex> lk(mu_);
  Observe(0);     // EXPECT cpp-excludes: callee acquires mu_ itself
  return value_;  // clean: under the lock scope
}

bool ParamTuner::Observe(int v) {
  std::lock_guard<std::mutex> lk(mu_);
  Apply(v);  // clean: mu_ held at the call site
  return value_ > 0;
}

void ParamTuner::Apply(int v) {
  value_ = v;  // clean: REQUIRES(mu_) — the caller holds the lock
}
