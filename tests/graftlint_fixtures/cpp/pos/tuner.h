// Positive fixture for the cpp-guarded-by / cpp-requires /
// cpp-excludes checks.  The annotation macros are never expanded here
// (the rule parses them textually); Configure's unlocked writes mirror
// the exact ParameterManager::Configure shape fixed in the live tree —
// reverting that fix re-creates what tuner.cc seeds.
#pragma once

#include <mutex>

class ParamTuner {
 public:
  void Configure(int v) EXCLUDES(mu_);
  bool Observe(int v) EXCLUDES(mu_);
  int Get() const EXCLUDES(mu_);
  void Flush() EXCLUDES(mu_, io_mu_);
  void Reset() EXCLUDES(mu_, io_mu_);

 private:
  void Apply(int v) REQUIRES(mu_);
  // Stacked annotations: BOTH contracts must be parsed and enforced.
  void Publish() REQUIRES(mu_) EXCLUDES(io_mu_);

  mutable std::mutex mu_;
  mutable std::mutex io_mu_;
  int value_ GUARDED_BY(mu_) = 0;
};
