"""Fixture bootstrap module: one documented knob, two undocumented."""

import os


def env_int(name, default, minimum=None):
    return default


def knobs():
    a = env_int("HOROVOD_BOOT_DOCUMENTED", 1)
    b = env_int("HOROVOD_BOOT_MISSING", 2)
    c = os.environ.get("HOROVOD_BOOT_RAW_MISSING")
    d = os.environ.get("NOT_A_KNOB")  # foreign prefix: out of scope
    return a, b, c, d
