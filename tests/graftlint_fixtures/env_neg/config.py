"""FIXTURE (clean): one read per key, every key documented."""
import os


def _env(name, default=None):
    v = os.environ.get("HVD_TPU_" + name)
    if v is None:
        v = os.environ.get("HOROVOD_" + name)
    return default if v is None else v


def _env_float(name, default):
    v = _env(name)
    return float(v) if v is not None else default


FUSION = _env("FUSION_THRESHOLD", "64")
CYCLE = _env_float("CYCLE_TIME", 5.0)
