"""FIXTURE: direct read, string default."""
import os

TIMEOUT = os.environ.get("HOROVOD_PING_TIMEOUT", "600")
