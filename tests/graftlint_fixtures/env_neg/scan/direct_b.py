"""FIXTURE (clean): same key, numerically identical default ("600" vs
600) — the comparison is numeric, not textual."""
import os

TIMEOUT = int(os.environ.get("HOROVOD_PING_TIMEOUT", 600))
