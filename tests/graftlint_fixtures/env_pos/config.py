"""FIXTURE (flags env-undocumented + env-duplicate-read)."""
import os


def _env(name, default=None):
    v = os.environ.get("HVD_TPU_" + name)
    if v is None:
        v = os.environ.get("HOROVOD_" + name)
    return default if v is None else v


FUSION = _env("FUSION_THRESHOLD", "64")
GHOST = _env("GHOST_KNOB")                  # documented nowhere
FUSION_AGAIN = _env("FUSION_THRESHOLD", "128")  # second read, new default
