"""FIXTURE: bootstrap-path direct read, default 600."""
import os

TIMEOUT = os.environ.get("HOROVOD_PING_TIMEOUT", "600")
