"""FIXTURE (flags env-default-conflict): same key, contradictory
default."""
import os

TIMEOUT = os.environ.get("HOROVOD_PING_TIMEOUT", "900")
