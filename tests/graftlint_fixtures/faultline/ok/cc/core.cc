// Fixture native plant: guard + fire at one seam is NOT a duplicate.
void Seam() {
  if (fault::Armed("c.core")) {
    fault::Point("c.core");
  }
}
