"""Fixture registry: every site planted, documented, unique."""

SITES = {
    "a.one": "python seam one",
    "b.two": "python seam two",
    "c.core": "native-core seam (guard + fire pair)",
}
