"""Fixture plants: one fire per site; an armed() guard is no plant."""

from somewhere import faultline


def seam_one():
    faultline.site("a.one")


def seam_two():
    if faultline.armed("b.two"):
        faultline.site("b.two")
