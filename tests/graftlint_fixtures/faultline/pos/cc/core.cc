// Fixture native plant of an unregistered site.
void Seam() { fault::Point("cc.unregistered"); }
