"""Fixture registry with every drift the rule must flag."""

SITES = {
    "a.one": "planted twice -> duplicate",
    "u.undoc": "planted but missing from docs -> undocumented",
    "d.orphan": "planted nowhere -> orphan",
}
