"""Fixture plants: duplicate fire, unregistered name."""

from somewhere import faultline


def seam_one():
    faultline.site("a.one")


def seam_one_again():
    faultline.site("a.one")  # duplicate: one seam per name


def undocumented():
    faultline.site("u.undoc")


def typo():
    faultline.site("zz.unregistered")
