"""env-harness-pin fixture: a spawn-style harness with one documented
pin, one ghost pin (EXPECT a finding), and a plain read that must NOT
count as a pin."""

import os


def spawn(worker):
    env = dict(os.environ)
    env.update({
        "HOROVOD_DOCUMENTED_PIN": "1",
    })
    env["HOROVOD_GHOST_PIN"] = "1"  # EXPECT env-harness-pin
    scale = os.environ.get("HOROVOD_SOME_READ", "1")  # read, not a pin
    return worker, env, scale
