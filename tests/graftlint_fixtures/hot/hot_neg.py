"""FIXTURE (clean): metadata-whitelisted np calls, a documented
crossing with a cited suppression, and host calls outside any
hot-path annotation."""
import numpy as np


def shape_math(lengths):  # graftlint: hot-path
    return int(np.prod(lengths, dtype=np.int64))


def staged(payload):  # graftlint: hot-path
    return np.asarray(payload)  # graftlint: disable=host-bounce issue=GL-1 -- documented staging point, counted by host_stages


def cold_path(payload):
    return np.asarray(payload)  # not annotated: out of scope
