"""FIXTURE (flags host-bounce): payload np call, .item(), and
device_get inside hot-path functions (nested closure included)."""
import numpy as np


def stage(payload):  # graftlint: hot-path
    return np.asarray(payload)


def fetch(x):  # graftlint: hot-path
    return x.item()


def dispatch(outs):  # graftlint: hot-path
    import jax

    def finalize():
        return jax.device_get(outs)
    return finalize
