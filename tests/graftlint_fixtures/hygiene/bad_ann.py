"""FIXTURE (flags bad-annotation twice): a typo'd annotation key and
an ownership annotation attached to no self-attribute write."""

FLAG = True  # graftlint: guarded-by=_lock


class C:
    def __init__(self):
        self.x = 1  # graftlint: gurded-by=_lock
