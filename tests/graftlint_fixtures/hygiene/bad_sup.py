"""FIXTURE (flags bad-suppression): suppression without an issue
citation — silencing a finding without a tracker entry is itself a
finding."""
import numpy as np


def stage(p):  # graftlint: hot-path
    return np.asarray(p)  # graftlint: disable=host-bounce -- a reason but no issue ref
