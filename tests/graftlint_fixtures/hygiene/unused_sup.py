"""FIXTURE (flags unused-suppression): the suppressed check matches
nothing on the line (np.prod is metadata-whitelisted)."""
import numpy as np


def ok(lengths):  # graftlint: hot-path
    n = int(np.prod(lengths))  # graftlint: disable=host-bounce issue=GL-2 -- nothing here to suppress
    return n
