"""Fixture registry: clean — every name declared once and planted."""

NAMES = {
    "good_total": ("counter", "a counted thing"),
    "depth": ("gauge", "a measured level"),
    "latency_seconds": ("histogram", "a timed thing"),
    "internal_total": ("counter", "used by the registry module itself"),
}


def counter(name, **labels):
    return None


def event(kind):
    counter("internal_total")
