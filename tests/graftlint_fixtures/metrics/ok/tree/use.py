"""Fixture call sites: every plant names a registered series with the
declared kind."""

metrics = None


def touch():
    metrics.counter("good_total").inc()
    metrics.gauge("depth").set(3)
    metrics.histogram("latency_seconds").observe(0.5)
