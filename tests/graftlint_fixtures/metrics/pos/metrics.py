"""Fixture registry: one duplicate declaration and one orphan."""

NAMES = {
    "x_total": ("counter", "used, fine"),
    "dup_total": ("counter", "declared twice"),
    "dup_total": ("counter", "the silent last-wins duplicate"),  # noqa: F601
    "orphan_total": ("counter", "declared but planted nowhere"),
}
