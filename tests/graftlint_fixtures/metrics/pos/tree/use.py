"""Fixture call sites: unregistered, non-literal and kind-mismatched
plants."""

metrics = None
DYNAMIC = "x_total"


def touch():
    metrics.counter("x_total").inc()          # fine
    metrics.counter("dup_total").inc()        # fine (keeps it non-orphan)
    metrics.counter("nope_total").inc()       # unregistered
    metrics.counter(DYNAMIC).inc()            # non-literal
    metrics.gauge("x_total").set(1)           # kind mismatch
