"""FIXTURE (clean): the per-dispatch callback threads through the call
instead of riding shared instance state."""


class Engine:
    def _execute(self, mc, wid):
        mc.dispatch(notify=lambda phase: self._watch_compile(wid, phase))

    def _watch_compile(self, wid, phase):
        pass
