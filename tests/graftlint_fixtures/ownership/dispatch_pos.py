"""FIXTURE (flags dispatch-scoped): the reverted ``compile_notify``
pattern from ops/multihost.py — per-dispatch callback parked on the
shared mesh object and reset after the call.  If the real fix is ever
reverted, the live tree reproduces exactly this shape and the
zero-findings baseline test fails."""


class Engine:
    def _execute(self, mc, wid):
        mc.compile_notify = lambda phase: self._watch_compile(wid, phase)
        try:
            mc.dispatch()
        finally:
            mc.compile_notify = None

    def _watch_compile(self, wid, phase):
        pass
