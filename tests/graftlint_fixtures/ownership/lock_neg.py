"""FIXTURE (clean): guarded writes via the Condition alias and the
requires-lock (caller-holds-it) convention."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._n = 0  # graftlint: guarded-by=_lock
        threading.Thread(target=self._tick, name="ticker").start()

    def _tick(self):
        with self._wake:  # Condition wrapping _lock satisfies the guard
            self._n += 1

    def bump_locked(self):  # graftlint: requires-lock=_lock
        self._n += 1
