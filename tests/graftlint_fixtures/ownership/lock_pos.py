"""FIXTURE (flags lock-discipline): ``_n`` is guarded-by=_lock but the
ticker thread writes it outside ``with self._lock``."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # graftlint: guarded-by=_lock
        threading.Thread(target=self._tick, name="ticker").start()

    def _tick(self):
        self._n += 1

    def bump(self):
        with self._lock:
            self._n += 1
