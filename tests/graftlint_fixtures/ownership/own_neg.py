"""FIXTURE (clean): same sharing as own_pos but annotated guarded-by
and every write under the lock."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = 0  # graftlint: guarded-by=_lock
        self._thread = threading.Thread(target=self._loop, name="worker")
        self._thread.start()

    def _loop(self):
        with self._lock:
            self._state = 1

    def poke(self):
        with self._lock:
            self._state = 2
