"""FIXTURE (flags ownership-shared): ``_state`` is written after
__init__ and touched from two thread contexts with no annotation."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = 0
        self._thread = threading.Thread(target=self._loop, name="worker")
        self._thread.start()

    def _loop(self):
        self._state = 1

    def poke(self):
        self._state = 2
