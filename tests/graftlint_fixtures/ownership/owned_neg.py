"""FIXTURE (clean): the owned attribute is touched only by its owner
thread (and __init__)."""
import threading


class Loop:
    def __init__(self):
        self._beat = 0  # graftlint: owned-by=pulse
        threading.Thread(target=self._run, name="pulse").start()

    def _run(self):
        self._beat += 1
