"""FIXTURE (flags owned-by): ``_beat`` is owned by the pulse thread but
a caller-facing method reads it."""
import threading


class Loop:
    def __init__(self):
        self._beat = 0  # graftlint: owned-by=pulse
        threading.Thread(target=self._run, name="pulse").start()

    def _run(self):
        self._beat += 1

    def read_beat(self):
        return self._beat
