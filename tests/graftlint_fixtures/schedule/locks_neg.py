"""Negative lock fixtures: nesting under one global order, and the
requires-lock caller-holds convention."""
import threading

_journal_lock = threading.Lock()


class Ordered:
    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()
        self._cv = threading.Condition(self._inner)

    def fast(self):
        with self._outer:
            with self._inner:
                pass

    def slow(self):
        # Same order as fast(): outer before inner, via the Condition
        # alias of the SAME underlying lock.
        with self._outer:
            with self._cv:
                pass

    def journal(self):
        with self._outer:
            append("x")


def append(line):
    with _journal_lock:
        _flush(line)


def _flush(line):  # graftlint: requires-lock=_journal_lock -- append() is the only caller
    return line
