"""Positive lock fixtures: an A->B / B->A inversion, both lexical and
through calls made while holding."""
import threading

_registry_lock = threading.Lock()


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass


class Caller:
    def __init__(self):
        self._mu = threading.Lock()

    def publish(self):
        # Holds _mu, and register() transitively acquires the registry
        # lock: _mu -> _registry_lock.
        with self._mu:
            register()

    def on_event(self):
        # The registry-side callback path takes the locks the other
        # way around: _registry_lock -> _mu.  Interprocedural cycle.
        with _registry_lock:
            self.refresh()

    def refresh(self):
        with self._mu:
            pass


def register():
    with _registry_lock:
        pass
