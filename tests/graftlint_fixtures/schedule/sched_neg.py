"""Negative schedule fixtures: rank-dependent code that stays
schedule-safe (and the annotations that prove or waive it)."""
import horovod_tpu as hvd


def data_conditioned(t):  # graftlint: schedule-entry=fixture -- golden-cert entry
    # Branching on tensor shape: uniform by construction (params are
    # assumed uniform), and both arms issue the same sequence anyway.
    if t.shape[0] > 1:
        hvd.allreduce(t)
    else:
        hvd.allreduce(t)
    hvd.barrier()
    return sorted_fanout([t])


def rank_only_side_effects(path, t):
    # Rank-dependent branch with NO collectives in either arm: fine.
    if hvd.rank() == 0:
        log = open(path, "w")
        log.write("lead\n")
        log.close()
    return hvd.allreduce(t)


def proven_uniform(flag, t):
    # The branch condition was allreduced first: every member computed
    # the SAME value, so conditioning collectives on it is safe — the
    # collective result is a taint barrier.
    joint = hvd.allreduce(flag)
    if joint > 0:
        hvd.allgather(t)


def declared_uniform(t):
    me = hvd.rank()
    lead = me == 0
    if lead:  # graftlint: spmd-uniform -- fixture: condition vouched uniform at a negotiated commit point
        hvd.allreduce(t)


def waived_order(named):  # graftlint: collective-order-exempt -- names registered via register_group; core matches by name not order
    for t in set(named):
        hvd.allreduce(t)


def sorted_fanout(named):
    # sorted() is the blessed determinizer for set iteration.
    for t in sorted(named):
        hvd.allreduce(t)
