"""Positive schedule fixtures: every def here trips a collective
schedule check (deadlock-shaped or order-divergent)."""
import horovod_tpu as hvd


def tainted_skip(t):
    # Rank-dependent branch where only one arm issues a collective:
    # rank 0 blocks in allreduce, every other rank never joins.
    if hvd.rank() == 0:
        hvd.allreduce(t)


def tainted_order(t, u):
    # Same collectives, different ORDER per rank: classic cross-rank
    # schedule mismatch (rank 0 waits in allreduce, rank 1 in
    # allgather).
    if hvd.rank() == 0:
        hvd.allreduce(t)
        hvd.allgather(u)
    else:
        hvd.allgather(u)
        hvd.allreduce(t)


def tainted_trip_count(ts):
    # Loop trip count derives from the local rank: ranks issue a
    # different NUMBER of collectives.
    for _ in range(hvd.rank()):
        hvd.allreduce(ts)


def set_iteration(named):
    # Collectives issued in set order: hash-seed-dependent, so the
    # per-rank sequences need not agree.
    for t in set(named):
        hvd.allreduce(t)


def taint_through_local(t):
    # The rank read flows through a local before conditioning the
    # branch; the dataflow pass must carry it.
    me = hvd.rank()
    lead = me == 0
    if lead:
        hvd.broadcast(t, root_rank=0)


def taint_interprocedural(t):
    # The rank read hides behind a helper's return value.
    if _is_lead():
        hvd.barrier()


def _is_lead():
    return hvd.rank() == 0
