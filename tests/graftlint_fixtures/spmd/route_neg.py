"""Negative fixture: route_pos's shapes made uniform via declared
barriers (cross-rank averaging, rank-0-publish -> blocking-adopt,
sorted iteration) plus the explicit-flow limit (rank-gated DATA is the
SPMD model; only routed VALUES matter).  Must lint clean."""


def rank():
    return 0


class PlanController:
    def __init__(self, plan):
        self.plan = plan

    def route(self, op, klass, default):
        return default


def _averaged_score(x):  # graftlint: spmd-uniform -- cross-rank mean over the collective plane: every member contributes its local score and receives the identical average
    return x


def adopt(kv):
    plan = kv.get_blocking("plan")  # graftlint: spmd-uniform -- rank-0-publish -> blocking-adopt: every member leaves with rank 0's blob or raises
    ctl = PlanController(plan)
    return ctl


def route_scored(ctl, score):
    s = _averaged_score(score)
    ctl.route("allreduce", s, True)


def publish_order(kv, names):
    acc = []
    for n in sorted(set(names)):
        acc.append(n)
    publish_kv(kv, acc)


def tune(kv, score):
    # A NESTED barrier def is opaque: its internals (which feed
    # per-rank scores into the shared publish by design) are vouched,
    # not re-litigated in this function's env.
    def avg(x):  # graftlint: spmd-uniform -- cross-rank mean: every member contributes and receives the identical average
        s = rank() + x
        publish_kv(kv, s)
        return s
    return avg(score)


def rank_gated_data(x):
    # Per-rank CONTROL over per-rank DATA: the test does not taint the
    # value (explicit flows only).
    return x * 2 if rank() > 0 else x
