"""Seeded spmd-uniform violations — every shape the rule must catch.

``adopt_local`` is the r14 divergent-routing bug, reconstructed: a
member with no KV to agree through routes by its own filesystem blob
while rank 0 routes by its plan — divergent XLA programs, distributed
hang.  The rest cover taint through a helper call, a wall-clock write
to a schedule lever, and set-iteration order feeding a published plan.
"""

import os
import time


def rank():
    return 0


class PlanController:
    def __init__(self, plan):
        self.plan = plan

    def route(self, op, klass, default):
        return default


def _tenant_gate():
    # Taint must survive the helper call: the per-rank env is read
    # here, the routing decision is in the caller.
    return os.environ.get("HOROVOD_TENANT_ID", "0")


def adopt_local(path):
    # r14 shape: no KV agreement, so this member steers routing by its
    # own per-host cache blob.
    blob = open(path).read()
    ctl = PlanController(blob)  # EXPECT spmd-uniform (filesystem)
    return ctl


def route_by_tenant(ctl):
    klass = _tenant_gate()
    ctl.route("allreduce", klass, True)  # EXPECT spmd-uniform (env)


def gate_in_condition(ctl):
    # The gate shape itself: a tainted routing call in an if-test.
    klass = rank()
    if ctl.route("allreduce", klass, True):  # EXPECT spmd-uniform
        return True
    return False


def pace_by_clock(engine):
    t = time.monotonic()
    engine.cycle_time_ms = t  # EXPECT spmd-uniform (clock -> lever)


def _route_via(ctl, klass):
    ctl.route("allreduce", klass, True)


def route_kw(ctl):
    # Keyword args must flow like positional ones through the callee's
    # parameter summaries.
    _route_via(ctl, klass=rank())  # EXPECT spmd-uniform (kw arg)


def publish_order(kv, names):
    acc = []
    for n in set(names):
        acc.append(n)
    publish_kv(kv, acc)  # EXPECT spmd-uniform (set-iteration order)
