"""Suppression hygiene for spmd-uniform: a real violation silenced by
a cited suppression lints clean; the citation rules are the shared
ones (bad_sup.py / unused_sup.py cover the failure modes)."""


def rank():
    return 0


def route_debug(ctl):
    klass = rank()
    ctl.route("debug", klass, True)  # graftlint: disable=spmd-uniform issue=ISSUE-10 -- debug-only path, never reaches a negotiated world
