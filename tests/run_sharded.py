"""Shard-by-file test runner: split the suite across machines/jobs.

The serial suite is ~16 min on a 1-core box; CI with several runners
can take shard k of M instead:

    python tests/run_sharded.py --shard 0/3
    python tests/run_sharded.py --shard 1/3
    python tests/run_sharded.py --shard 2/3

Files are partitioned deterministically by LPT (longest-processing-
time-first) over recorded per-file durations, so shards are balanced
and stable across invocations — every file runs in exactly one shard.
Extra pytest args pass through after ``--``:

    python tests/run_sharded.py --shard 1/2 -- -x -q

Each shard is a separate pytest process, so the spawn harness's
port-range isolation (tests/utils/spawn.py honors
``HVD_TPU_TEST_PORT_SHARD`` here the same way it honors
``PYTEST_XDIST_WORKER``) keeps concurrent shards on one host from
colliding.  For in-process parallelism on a multi-core host, plain
``pytest -n N --dist loadfile`` also works (ports are xdist-safe).
"""

from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys

# Approximate serial durations (seconds) recorded on the 1-core build
# box, 2026-07-31.  Files not listed default to 10 s; exact values only
# matter for balance, not correctness.
RECORDED_SECONDS = {
    "test_tf_adapter.py": 205,
    "test_tcp_core.py": 150,
    "test_elastic.py": 140,
    "test_multihost.py": 130,
    "test_bench_smoke.py": 345,
    "test_torch_adapter.py": 120,
    "test_platform_contract.py": 90,
    "test_basics.py": 80,
    "test_keras_adapter.py": 60,
    "test_transformer.py": 55,
    "test_bert.py": 40,
    "test_spark_estimators.py": 45,
    "test_runner.py": 45,
    "test_collectives.py": 30,
    "test_sequence_parallel.py": 25,
    "test_pallas_kernels.py": 25,
    "test_moe_pipeline.py": 20,
    "test_jax_adapter.py": 20,
    "test_zero.py": 15,
    "test_pallas_bn.py": 15,
}


def partition(files, n_shards):
    """Deterministic LPT: heaviest file to the lightest shard."""
    weights = {f: RECORDED_SECONDS.get(os.path.basename(f), 10)
               for f in files}
    order = sorted(files, key=lambda f: (-weights[f], f))
    shards = [[] for _ in range(n_shards)]
    loads = [0.0] * n_shards
    for f in order:
        i = loads.index(min(loads))
        shards[i].append(f)
        loads[i] += weights[f]
    return [sorted(s) for s in shards], loads


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shard", required=True,
                    help="k/M — run shard k (0-based) of M")
    ap.add_argument("--list", action="store_true",
                    help="print the file partition and exit")
    ap.add_argument("rest", nargs=argparse.REMAINDER,
                    help="extra pytest args after --")
    args = ap.parse_args()
    k, m = (int(v) for v in args.shard.split("/"))
    if not (0 <= k < m):
        raise SystemExit("--shard k/M needs 0 <= k < M")

    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "test_*.py")))
    shards, loads = partition(files, m)
    if args.list:
        for i, (s, w) in enumerate(zip(shards, loads)):
            print("shard %d/%d (~%ds): %s" % (
                i, m, w, " ".join(os.path.basename(f) for f in s)))
        return 0
    rest = [a for a in args.rest if a != "--"] or ["-q"]
    env = dict(os.environ)
    # Disjoint spawn-port ranges per shard (mirrors the xdist handling
    # in tests/utils/spawn.py).
    env["HVD_TPU_TEST_PORT_SHARD"] = str(k)
    cmd = [sys.executable, "-m", "pytest", *shards[k], *rest]
    print("shard %d/%d: %d files (~%ds serial)" % (
        k, m, len(shards[k]), loads[k]), flush=True)
    return subprocess.call(cmd, env=env, cwd=os.path.dirname(here))


if __name__ == "__main__":
    raise SystemExit(main())
