"""Shard-by-file test runner: split the suite across machines/jobs.

The serial suite is ~16 min on a 1-core box; CI with several runners
can take shard k of M instead:

    python tests/run_sharded.py --shard 0/3
    python tests/run_sharded.py --shard 1/3
    python tests/run_sharded.py --shard 2/3

Files are partitioned deterministically by LPT (longest-processing-
time-first) over recorded per-file durations, so shards are balanced
and stable across invocations — every file runs in exactly one shard.
Extra pytest args pass through after ``--``:

    python tests/run_sharded.py --shard 1/2 -- -x -q

Each shard is a separate pytest process, so the spawn harness's
port-range isolation (tests/utils/spawn.py honors
``HVD_TPU_TEST_PORT_SHARD`` here the same way it honors
``PYTEST_XDIST_WORKER``) keeps concurrent shards on one host from
colliding.  For in-process parallelism on a multi-core host, plain
``pytest -n N --dist loadfile`` also works (ports are xdist-safe).
"""

from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys

# MEASURED serial durations (seconds): junitxml sums from the recorded
# green 2-shard run of 2026-08-01 (tests/README.md), 1-core box,
# HVD_TPU_TEST_TIMEOUT_SCALE=2.  junit time excludes per-file
# collection/import (~5-10 s on this box), so small files floor at 5;
# exact values only matter for balance, not correctness.  Re-record
# with ``--record-durations``.
RECORDED_SECONDS = {
    "test_bench_smoke.py": 275,
    "test_elastic.py": 220,  # measured 101 + the r5 watchdog-recovery
    "test_tcp_core.py": 114,
    "test_platform_contract.py": 99,
    "test_torch_adapter.py": 98,
    "test_tf_adapter.py": 97,
    "test_transformer.py": 92,
    "test_multihost.py": 76,
    "test_runner.py": 49,
    "test_spark_estimators.py": 48,
    "test_basics.py": 40,
    "test_bert.py": 36,
    "test_pallas_kernels.py": 25,
    "test_moe_pipeline.py": 19,
    "test_collectives.py": 11,
    "test_podcheck.py": 10,
    "test_pallas_bn.py": 8,
    "test_sequence_parallel.py": 5,
}


def partition(files, n_shards):
    """Deterministic LPT: heaviest file to the lightest shard."""
    weights = {f: RECORDED_SECONDS.get(os.path.basename(f), 10)
               for f in files}
    order = sorted(files, key=lambda f: (-weights[f], f))
    shards = [[] for _ in range(n_shards)]
    loads = [0.0] * n_shards
    for f in order:
        i = loads.index(min(loads))
        shards[i].append(f)
        loads[i] += weights[f]
    return [sorted(s) for s in shards], loads


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shard", required=True,
                    help="k/M — run shard k (0-based) of M")
    ap.add_argument("--list", action="store_true",
                    help="print the file partition and exit")
    ap.add_argument("--record-durations", action="store_true",
                    help="write junitxml and print measured per-file "
                         "seconds in RECORDED_SECONDS form")
    ap.add_argument("rest", nargs=argparse.REMAINDER,
                    help="extra pytest args after --")
    args = ap.parse_args()
    k, m = (int(v) for v in args.shard.split("/"))
    if not (0 <= k < m):
        raise SystemExit("--shard k/M needs 0 <= k < M")

    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "test_*.py")))
    shards, loads = partition(files, m)
    if args.list:
        for i, (s, w) in enumerate(zip(shards, loads)):
            print("shard %d/%d (~%ds): %s" % (
                i, m, w, " ".join(os.path.basename(f) for f in s)))
        return 0
    rest = [a for a in args.rest if a != "--"] or ["-q"]
    env = dict(os.environ)
    # Disjoint spawn-port ranges per shard (mirrors the xdist handling
    # in tests/utils/spawn.py).
    env["HVD_TPU_TEST_PORT_SHARD"] = str(k)
    xml = None
    if args.record_durations:
        xml = os.path.join(here, ".shard%d_durations.xml" % k)
        rest = rest + ["--junitxml", xml]
    cmd = [sys.executable, "-m", "pytest", *shards[k], *rest]
    print("shard %d/%d: %d files (~%ds serial)" % (
        k, m, len(shards[k]), loads[k]), flush=True)
    rc = subprocess.call(cmd, env=env, cwd=os.path.dirname(here))
    if xml and os.path.exists(xml):
        _print_file_durations(xml)
    return rc


def _print_file_durations(xml_path):
    """Aggregate junitxml per-test times into per-FILE seconds — the
    measured values for RECORDED_SECONDS."""
    import collections
    import xml.etree.ElementTree as ET
    per_file = collections.Counter()
    for case in ET.parse(xml_path).getroot().iter("testcase"):
        cls = case.get("classname", "")
        mod = next((p for p in cls.split(".")
                    if p.startswith("test_")), None)
        per_file[(mod + ".py") if mod else "?"] += \
            float(case.get("time", 0))
    print("# measured per-file seconds (junitxml sum):")
    for fname, secs in sorted(per_file.items(), key=lambda kv: -kv[1]):
        print('    "%s": %d,' % (fname, round(secs)))


if __name__ == "__main__":
    raise SystemExit(main())
