"""Autotuner behavior tests (Python mirror of parameter_manager.cc +
bayesian_optimization.cc; the C++ twin is driven by the tcp worlds)."""

import numpy as np

import pytest

from horovod_tpu.utils.autotune import (BayesianOptimizer,
                                        GaussianProcess,
                                        KernelBlockTuner,
                                        ParameterManager,
                                        expected_improvement)


def test_gp_fits_and_predicts():
    gp = GaussianProcess(length_scale=1.0)
    x = np.array([[0.0], [1.0], [2.0]])
    y = np.array([0.0, 1.0, 0.0])
    gp.fit(x, y)
    mu, sigma = gp.predict(np.array([[1.0], [10.0]]))
    # near a training point: confident and close; far away: uncertain
    assert abs(mu[0] - 1.0) < 0.2
    assert sigma[1] > sigma[0]


def test_expected_improvement_prefers_uncertain_high_mean():
    mu = np.array([0.0, 1.0, 1.0])
    sigma = np.array([0.1, 0.1, 1.0])
    ei = expected_improvement(mu, sigma, best=0.5)
    assert ei[2] > ei[1] > ei[0]


def test_bayesian_optimizer_converges_to_better_region():
    bo = BayesianOptimizer()
    # synthetic objective: reward large fusion + small cycle (the
    # common real-world optimum); the BO should concentrate samples
    # toward the high-scoring corner
    rng = np.random.default_rng(0)
    for _ in range(20):
        idx = bo.next_index()
        f_log, c_log = bo.grid[idx]
        score = float(2 * f_log - c_log + rng.normal(0, 0.1))
        bo.record(idx, score)
    best = bo.grid[bo.best_index()]
    assert best[0] >= np.median(bo.grid[:, 0])  # large fusion chosen


def test_gp_length_scale_fit_recovers_smoothness():
    # Samples from a smooth surface (true scale ~2) vs a jagged one
    # (scale ~0.2): the max-marginal-likelihood fit must order the
    # learned length-scales accordingly — that is exactly the sample-
    # efficiency knob the fixed-scale GP lacked.
    x = np.linspace(0.0, 6.0, 24)[:, None]
    smooth = np.sin(x[:, 0] / 2.0)
    jagged = np.sin(x[:, 0] * 8.0)
    gp_s = GaussianProcess(noise=1e-4)
    gp_s.fit(x, smooth, optimize_length_scale=True)
    gp_j = GaussianProcess(noise=1e-4)
    gp_j.fit(x, jagged, optimize_length_scale=True)
    assert gp_s.length_scale > 1.0, gp_s.length_scale
    assert gp_j.length_scale < 0.5, gp_j.length_scale
    assert gp_s.length_scale > 3 * gp_j.length_scale
    # The refit GP interpolates the smooth surface well between samples.
    mu, _ = gp_s.predict(np.array([[1.1]]))
    assert abs(mu[0] - np.sin(1.1 / 2.0)) < 0.05


def test_bo_with_ls_fit_converges_on_synthetic_throughput_surface():
    # Synthetic throughput surface with a known interior optimum (not a
    # grid corner): fusion sweet spot at ~2^24 with a cycle-time
    # penalty.  After a budget of samples, the chosen point must sit in
    # the top decile of the true surface — the convergence bar for the
    # hyperparameter-fitting BO.
    bo = BayesianOptimizer()

    def surface(f_log, c_log):
        return -((f_log - 24.0) ** 2) - 0.5 * (c_log - 1.0) ** 2

    rng = np.random.default_rng(1)
    for _ in range(16):
        idx = bo.next_index()
        f_log, c_log = bo.grid[idx]
        bo.record(idx, float(surface(f_log, c_log)
                             + rng.normal(0, 0.05)))
    truth = np.array([surface(f, c) for f, c in bo.grid])
    chosen = truth[bo.best_index()]
    assert chosen >= np.quantile(truth, 0.9), (
        chosen, float(truth.max()))


def test_parameter_manager_samples_and_freezes(tmp_path):
    # The r14 crash-safe writer rank-stamps the path (one writer per
    # file); pin the tag so the read-back path is deterministic.
    log = tmp_path / "autotune.csv.r0"
    pm = ParameterManager(fusion_threshold=1 << 20, cycle_time_ms=5.0,
                          log_path=str(tmp_path / "autotune.csv"),
                          warmup=1, steps_per_sample=2, max_samples=3,
                          log_tag="r0")
    # throughput is higher for larger fusion thresholds
    for _ in range(1 + 2 * 3 + 2):
        pm.observe(nbytes=pm.fusion_threshold, secs=1e-3)
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("sample,")
    assert len(lines) >= 4  # header + 3 samples
    # after max_samples the manager settles on the best point
    settled = pm.fusion_threshold
    pm.observe(nbytes=123, secs=1e-3)
    assert pm.fusion_threshold == settled


def test_kernel_block_tuner_argmax_by_mean():
    t = KernelBlockTuner([(64, 64), (128, 128), (256, 256)])
    t.record(0, 50.0)
    t.record(1, 80.0)
    t.record(1, 100.0)   # mean 90 — repeated samples average
    t.record(0, 60.0)    # mean 55
    assert t.best() == (128, 128)
    assert t.samples() == 4
    v = t.scores_vector()
    assert v[1] == 90.0 and v[0] == 55.0
    # unsampled choices are -inf: fixed-length vector for the cross-
    # rank mean, and an unsampled choice can never win the argmax
    assert v[2] == -np.inf


def test_kernel_block_tuner_guards():
    with pytest.raises(ValueError):
        KernelBlockTuner([])
    t = KernelBlockTuner([(64, 64)])
    with pytest.raises(RuntimeError):
        t.best()
    with pytest.raises(IndexError):
        t.record(3, 1.0)


def test_engine_skips_observations_on_compile_cycles(hvd_world):
    # A cycle that compiled a new XLA executable must not feed its
    # wall time to the tuner (it measures the compiler, not comm).
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    class FakePM:
        fusion_threshold = 1 << 20
        cycle_time_ms = 1.0

        def __init__(self):
            self.observed = []

        def observe(self, nbytes, secs):
            self.observed.append(nbytes)

    eng = basics._get_engine()
    pm, old = FakePM(), eng.parameter_manager
    eng.parameter_manager = pm
    try:
        # fresh odd single-tensor shape -> this cycle compiles
        x = np.ones((hvd.size(), 97), np.float32)
        hvd.allreduce(x, op=hvd.Sum, name="atune_compile_skip_1")
        after_compile = len(pm.observed)
        # same shape again -> cached executable, observation recorded
        hvd.allreduce(x, op=hvd.Sum, name="atune_compile_skip_2")
        assert after_compile == 0, "compile cycle was observed"
        assert len(pm.observed) >= 1, "steady-state cycle not observed"
    finally:
        eng.parameter_manager = old
