"""Autotuner behavior tests (Python mirror of parameter_manager.cc +
bayesian_optimization.cc; the C++ twin is driven by the tcp worlds)."""

import numpy as np

from horovod_tpu.utils.autotune import (BayesianOptimizer,
                                        GaussianProcess,
                                        ParameterManager,
                                        expected_improvement)


def test_gp_fits_and_predicts():
    gp = GaussianProcess(length_scale=1.0)
    x = np.array([[0.0], [1.0], [2.0]])
    y = np.array([0.0, 1.0, 0.0])
    gp.fit(x, y)
    mu, sigma = gp.predict(np.array([[1.0], [10.0]]))
    # near a training point: confident and close; far away: uncertain
    assert abs(mu[0] - 1.0) < 0.2
    assert sigma[1] > sigma[0]


def test_expected_improvement_prefers_uncertain_high_mean():
    mu = np.array([0.0, 1.0, 1.0])
    sigma = np.array([0.1, 0.1, 1.0])
    ei = expected_improvement(mu, sigma, best=0.5)
    assert ei[2] > ei[1] > ei[0]


def test_bayesian_optimizer_converges_to_better_region():
    bo = BayesianOptimizer()
    # synthetic objective: reward large fusion + small cycle (the
    # common real-world optimum); the BO should concentrate samples
    # toward the high-scoring corner
    rng = np.random.default_rng(0)
    for _ in range(20):
        idx = bo.next_index()
        f_log, c_log = bo.grid[idx]
        score = float(2 * f_log - c_log + rng.normal(0, 0.1))
        bo.record(idx, score)
    best = bo.grid[bo.best_index()]
    assert best[0] >= np.median(bo.grid[:, 0])  # large fusion chosen


def test_parameter_manager_samples_and_freezes(tmp_path):
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(fusion_threshold=1 << 20, cycle_time_ms=5.0,
                          log_path=str(log), warmup=1,
                          steps_per_sample=2, max_samples=3)
    # throughput is higher for larger fusion thresholds
    for _ in range(1 + 2 * 3 + 2):
        pm.observe(nbytes=pm.fusion_threshold, secs=1e-3)
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("sample,")
    assert len(lines) >= 4  # header + 3 samples
    # after max_samples the manager settles on the best point
    settled = pm.fusion_threshold
    pm.observe(nbytes=123, secs=1e-3)
    assert pm.fusion_threshold == settled
