"""Lifecycle + identity API tests (reference: test/parallel/test_*.py
init/rank/size cases and test/single basics)."""

import os

import numpy as np
import pytest

import horovod_tpu as hvd


def test_init_rank_size(hvd_world):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def test_double_init_is_noop(hvd_world):
    hvd.init()
    assert hvd.size() == 8


def test_shutdown_and_reinit():
    hvd.init()
    assert hvd.is_initialized()
    hvd.shutdown()
    assert not hvd.is_initialized()
    hvd.init()
    assert hvd.size() == 8
    hvd.shutdown()


def test_uninitialized_raises():
    hvd.shutdown()
    with pytest.raises(RuntimeError):
        hvd.rank()


def test_built_probes(hvd_world):
    assert hvd.xla_built()
    assert not hvd.nccl_built()
    assert not hvd.mpi_built()
    assert not hvd.cuda_built()
    assert not hvd.mpi_enabled()
    assert not hvd.mpi_threads_supported()


def test_process_set_registry(hvd_world):
    ps = hvd.add_process_set([0, 1, 2])
    assert ps.process_set_id is not None
    assert ps.size() == 3
    assert ps.process_set_id in hvd.process_set_ids()
    # Duplicate registration rejected.
    with pytest.raises(ValueError):
        hvd.add_process_set(hvd.ProcessSet([0, 1, 2]))
    # Out-of-range ranks rejected.
    with pytest.raises(ValueError):
        hvd.add_process_set([0, 99])
    assert hvd.remove_process_set(ps)
    assert not hvd.remove_process_set(ps)  # already gone


def test_process_set_registry_reset_rederives_and_drops_dangling():
    """Pinned semantics of ``reset(world_size)`` across an elastic
    resize: sets whose ranks fit the new world SURVIVE (ids renumbered
    densely in registration order, identical on every rank); sets
    holding ranks >= the new world size are dropped LOUDLY — their
    ``process_set_id`` detaches to None so stale handles raise instead
    of silently aliasing a recycled id."""
    from horovod_tpu.common import process_sets as psm
    psm.reset_registry()
    try:
        a = psm.ProcessSet([0, 1])
        b = psm.ProcessSet([1, 3])    # rank 3 dies in a shrink to 2
        c = psm.ProcessSet([0])
        for ps in (a, b, c):
            psm._table.add(ps)
        assert (a.process_set_id, b.process_set_id,
                c.process_set_id) == (1, 2, 3)
        survivors = psm.reset_registry(world_size=2)
        assert survivors == [a, c]
        # Dense renumbering in the original registration order.
        assert (a.process_set_id, c.process_set_id) == (1, 2)
        assert psm.process_set_ids() == [0, 1, 2]
        # The dangling set detached loudly: its handle cannot resolve.
        assert b.process_set_id is None
        with pytest.raises(KeyError):
            psm.process_set_by_id(b.process_set_id)
        # A full wipe (no world size) detaches EVERY registered set, so
        # a recycled id can only ever name a set registered after it.
        psm.reset_registry()
        assert a.process_set_id is None and c.process_set_id is None
        fresh = psm.ProcessSet([0])
        psm._table.add(fresh)
        assert fresh.process_set_id == 1  # recycled by a NEW set only
    finally:
        psm.reset_registry()


def test_init_process_sets_idempotent_across_reinit():
    """Registrations survive shutdown()+init(), so a second
    init(process_sets=...) with the same sets must REUSE the survivors
    (same object or equal ranks) instead of tripping the
    duplicate-ranks check mid-init."""
    hvd.shutdown()
    ps = hvd.ProcessSet([0, 1])
    try:
        hvd.init(process_sets=[ps])
        assert ps.process_set_id == 1
        hvd.shutdown()
        hvd.init(process_sets=[ps])          # same object: reused
        assert ps.process_set_id == 1
        hvd.shutdown()
        hvd.init(process_sets=[[0, 1]])      # equal ranks: reused too
        assert hvd.process_set_ids() == [0, 1]
        assert ps.process_set_id == 1
    finally:
        hvd.remove_process_set(ps)
        hvd.shutdown()


def test_config_env_parsing(monkeypatch):
    from horovod_tpu.common.config import Config
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(1 << 20))
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "99")
    monkeypatch.setenv("HOROVOD_LOG_LEVEL", "INFO")
    monkeypatch.setenv("HOROVOD_TIMELINE", "/tmp/tl.json")
    c = Config.from_env()
    assert c.fusion_threshold_bytes == 1 << 20
    assert c.cycle_time_ms == 2.5
    assert c.cache_capacity == 99
    assert c.log_level == "info"
    assert c.timeline == "/tmp/tl.json"
    # HVD_TPU_* alias wins over HOROVOD_*.
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME", "7")
    assert Config.from_env().cycle_time_ms == 7.0


def test_vgg16_forward_and_loss():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.models.vgg import create_vgg16, vgg_loss_fn
    model = create_vgg16(num_classes=10, dtype=jnp.float32)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    nll, new_state = vgg_loss_fn(model, variables,
                                 {"x": x, "y": np.array([1, 2])})
    assert np.isfinite(float(nll)) and new_state == {}


def test_resnet101_forward_and_loss():
    # The reference's published scaling row pairs ResNet-101 with
    # Inception-V3 (BASELINE.md); the deeper stack must build and
    # train-step like ResNet-50.
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.models.resnet import (create_resnet101,
                                           resnet_loss_fn)
    model = create_resnet101(num_classes=10, dtype=jnp.float32)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    nll, new_state = resnet_loss_fn(model, variables,
                                    {"x": x, "y": np.array([1, 2])})
    assert np.isfinite(float(nll)) and "batch_stats" in new_state


def test_checkpoint_save_restore(tmp_path, hvd_world):
    import numpy as np
    import jax.numpy as jnp
    from horovod_tpu.utils.checkpoint import (all_steps, latest_step,
                                              restore_checkpoint,
                                              save_checkpoint)
    state = {"w": jnp.arange(4, dtype=jnp.float32), "step": 3}
    save_checkpoint(str(tmp_path), 3, state)
    save_checkpoint(str(tmp_path), 7, {"w": jnp.ones(4), "step": 7})
    assert all_steps(str(tmp_path)) == [3, 7]
    assert latest_step(str(tmp_path)) == 7
    got = restore_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(got["w"]), np.ones(4))
    got3 = restore_checkpoint(str(tmp_path), step=3)
    np.testing.assert_array_equal(np.asarray(got3["w"]), np.arange(4))
    # keep= prunes old steps
    save_checkpoint(str(tmp_path), 9, {"w": jnp.zeros(2), "step": 9},
                    keep=2)
    assert all_steps(str(tmp_path)) == [7, 9]
