"""bench.py is the driver-recorded artifact (BENCH_r*.json): a broken
harness loses the round's tracked metric, so smoke it on the CPU
fallback with a tiny config and validate the JSON contract."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_one_valid_json_line():
    env = dict(os.environ)
    # PYTHONPATH both makes the repo importable and (on the axon box)
    # keeps the TPU plugin out of the subprocess, forcing the CPU path.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["HVD_TPU_BENCH_BATCH"] = "2"
    env["HVD_TPU_BENCH_IMAGE"] = "32"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, "exactly one JSON line expected: %r" % lines
    d = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "step_ms", "batch", "peak_tflops", "device_kind"):
        assert key in d, key
    assert d["metric"] == "resnet50_images_per_sec_per_chip"
    assert d["value"] > 0 and d["step_ms"] > 0
