"""bench.py is the driver-recorded artifact (BENCH_r*.json): a broken
harness loses the round's tracked metric, so smoke it on the CPU
fallback with a tiny config and validate the JSON contract."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_one_valid_json_line():
    env = dict(os.environ)
    # PYTHONPATH both makes the repo importable and (on the axon box)
    # keeps the TPU plugin out of the subprocess, forcing the CPU path.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["HVD_TPU_BENCH_BATCH"] = "2"
    env["HVD_TPU_BENCH_IMAGE"] = "32"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, "exactly one JSON line expected: %r" % lines
    d = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "step_ms", "batch", "peak_tflops", "device_kind"):
        assert key in d, key
    assert d["metric"] == "resnet50_images_per_sec_per_chip"
    assert d["value"] > 0 and d["step_ms"] > 0
    # r9: per-lever attribution block — flash block plan + bwd variant
    # + hier-op mode, so a BENCH delta is attributable to one lever.
    lev = d["levers"]
    flash = lev["flash"]
    assert flash["source"] in ("env", "autotuned", "default",
                               "fallback_xla")
    assert flash["bwd"] in ("pallas", "pallas_onepass", "chunked")
    assert "block_q" in flash and "block_k" in flash
    assert lev["hier"]["mode"] in ("auto", "on", "off")
    assert set(lev["hier"]["ops"]) == {
        "allreduce", "allgather", "alltoall", "reducescatter",
        "broadcast"}
    # Collective-plan plane attribution (the persistent plan cache):
    # present even when the plane is off — the bench must always say
    # whether a warm start was in play.
    plan = lev["plan"]
    assert "enabled" in plan and "schema" in plan
    assert set(plan["apply"]) == {"cache", "kv", "tuned", "default"}
    assert "hits" in plan and "misses" in plan
    # r16 serving-plane attribution: the continuous-batching knobs +
    # autoscale policy + plan-cache warm-start a deployment would run
    # with (additive key; headline comes from serving_bw.py).
    serving = lev["serving"]
    assert serving["max_batch"] >= 1
    assert serving["max_wait_micros"] >= 0
    assert set(serving["autoscale"]) == {
        "up_qdepth", "down_qdepth", "interval_s", "cooldown_s"}
    assert serving["autoscale"]["up_qdepth"] > \
        serving["autoscale"]["down_qdepth"]
    assert set(serving["plan_warm_start"]) == {
        "enabled", "source", "hits"}
    # ISSUE 18 self-healing data-plane attribution: the deadline /
    # retry / degradation knobs plus the live failure evidence.
    res = lev["resilience"]
    for key in ("deadline_secs", "leg_max_retries", "demote_threshold",
                "reprobe_secs", "degrade_enabled", "wire_integrity",
                "demoted_routes", "leg_retries_total",
                "deadline_expired_total", "failures_by_reason"):
        assert key in res, key
    assert res["demoted_routes"] == []  # a clean bench run stays hier
    # ISSUE 19 steady-state fast-path attribution: frozen/thaw counters
    # + per-plane freezer state (additive key; present even when no
    # engine ran, degraded to counters-only).
    fp = lev["fastpath"]
    for key in ("frozen_cycles_total", "thaws_total", "thaws_by_reason",
                "planes"):
        assert key in fp, key


def test_allreduce_bw_amortization_math():
    # The small-message batching: a fake 2 us/op timer must be batched
    # up until the differential window clears the tunnel resolution,
    # and the recovered per-op time must stay exact.
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from allreduce_bw import bus_bytes, measure_per_op

    per_op_true = 2e-6

    def fake_timed(total_ops):
        return 1e-4 + per_op_true * total_ops  # fixed dispatch + ops

    per_op, opw, resolvable = measure_per_op(fake_timed, 10)
    assert resolvable
    assert opw > 10, "small ops were not amortized"
    assert abs(per_op - per_op_true) / per_op_true < 0.01
    # a big op needs no batching
    per_op2, opw2, r2 = measure_per_op(lambda k: 1e-3 * k, 10)
    assert r2 and opw2 == 10 and abs(per_op2 - 1e-3) < 1e-5
    # NCCL bus-bytes conventions
    assert bus_bytes("allreduce", 4, 100) == 2 * 3 / 4 * 100
    assert bus_bytes("allgather", 4, 100) == 3 * 100
    assert bus_bytes("reducescatter", 4, 100) == 3 / 4 * 100
    assert bus_bytes("alltoall", 4, 100) == 3 / 4 * 100
    assert bus_bytes("broadcast", 4, 100) == 3 / 4 * 100


def test_allreduce_bw_fault_leg_self_attributes():
    # The resilience A/B leg: --fault arms HVD_TPU_FAULT pre-init (the
    # parse-time registration of the new mh.leg.* drop sites is part of
    # what this proves) and the run ends with a self-attributing
    # resilience_levers JSON line.  The in-process CPU world has no
    # cross-host leg, so the armed fault must parse cleanly and the
    # run stay healthy — the evidence block shows zero demotions.
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HVD_TPU_FAULT", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "allreduce_bw.py"),
         "--eager", "--cpu-devices", "2", "--sizes-mb", "0.25",
         "--iters", "2", "--warmup", "1",
         "--fault", "mh.leg.drop:drop@times=1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    recs = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    lev = [r for r in recs if r.get("metric") == "resilience_levers"]
    assert len(lev) == 1, recs
    assert lev[0]["fault"] == "mh.leg.drop:drop@times=1"
    res = lev[0]["levers"]["resilience"]
    for key in ("deadline_secs", "deadline_per_gib", "leg_max_retries",
                "leg_retry_backoff", "demote_threshold", "reprobe_secs",
                "degrade_enabled", "wire_integrity", "demoted_routes",
                "leg_retries_total", "deadline_expired_total",
                "failures_by_reason"):
        assert key in res, key
    assert res["demoted_routes"] == []
    # the bandwidth records themselves still printed (the A/B numbers)
    assert [r for r in recs
            if r.get("metric") == "allreduce_bus_bandwidth"], recs


def test_allreduce_bw_fast_path_leg_self_attributes():
    # The fast-path A/B leg: --fast-path on exports HOROVOD_FAST_PATH
    # pre-init, the warm streak trips on the in-process engine, and the
    # run ends with a self-attributing fastpath_levers JSON line whose
    # frozen-cycle count (negotiations skipped) is the A/B evidence.
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["HOROVOD_FAST_PATH_WARM_CYCLES"] = "3"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "allreduce_bw.py"),
         "--eager", "--cpu-devices", "2", "--sizes-mb", "0.25",
         "--iters", "4", "--warmup", "2", "--fast-path", "on"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    recs = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    bw = [r for r in recs
          if r.get("metric") == "allreduce_bus_bandwidth"]
    assert bw, recs
    # per-size live-metrics reporting rode along
    for key in ("negotiation_cycles", "negotiation_cycles_skipped",
                "cycle_time_us"):
        assert key in bw[0], key
    lev = [r for r in recs if r.get("metric") == "fastpath_levers"]
    assert len(lev) == 1, recs
    fp = lev[0]["levers"]["fastpath"]
    assert fp["frozen_cycles_total"] > 0, fp  # negotiations skipped
    assert fp["planes"]["eager"]["enabled"] is True
    # the off leg must really negotiate every cycle
    env["HOROVOD_FAST_PATH"] = "1"  # ambient on; the flag must win
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "allreduce_bw.py"),
         "--eager", "--cpu-devices", "2", "--sizes-mb", "0.25",
         "--iters", "2", "--warmup", "1", "--fast-path", "off"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    recs = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    lev = [r for r in recs if r.get("metric") == "fastpath_levers"]
    assert len(lev) == 1, recs
    assert lev[0]["levers"]["fastpath"]["frozen_cycles_total"] == 0
    assert lev[0]["levers"]["fastpath"]["planes"]["eager"]["enabled"] \
        is False


def test_flash_roofline_smoke_schema():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "flash_roofline.py"),
         "--cpu-smoke"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    recs = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    by_metric = {}
    for r in recs:
        by_metric.setdefault(r["metric"], []).append(r)
    assert by_metric["flash_block_sweep"], recs
    variants = {r["variant"] for r in by_metric["flash_bwd_variant"]
                if "error" not in r}
    assert variants == {"pallas", "pallas_onepass", "chunked"}
    summary = by_metric["flash_roofline"][0]
    for key in ("matmul_roofline_tflops", "best_block_q",
                "best_block_k", "best_bwd_variant",
                "best_fwd_frac_of_roofline"):
        assert key in summary, key
