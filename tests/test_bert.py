"""BERT encoder tests: fine-tune/MLM training over a (dp, tp) mesh,
sharded-vs-single-device equivalence, padding-mask semantics.

Covers BASELINE.json configs[2] ("PyTorch BERT-large fine-tune") as a
native model family; the torch-adapter realization of the same
workload lives in ``examples/pytorch_bert_finetune.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.bert import (BertConfig, classification_loss,
                                     encode, init_params,
                                     make_finetune_step, mlm_loss,
                                     param_specs)

VOCAB = 64


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4,
                d_ff=64, max_seq=32, n_classes=3, dtype="float32")
    base.update(kw)
    return BertConfig(**base)


def _mesh(shape, names):
    devs = np.asarray(jax.devices()).reshape(shape)
    return Mesh(devs, names)


def _batch(rng, b, s, with_mask=False):
    batch = {
        "tokens": rng.randint(0, VOCAB, size=(b, s)).astype(np.int32),
        "labels": rng.randint(0, 3, size=(b,)).astype(np.int32),
    }
    if with_mask:
        mask = np.ones((b, s), np.int32)
        mask[:, s // 2:] = 0  # right-half padding
        batch["mask"] = mask
    return batch


def test_bert_finetune_trains_dp_tp(hvd_world):
    cfg = _cfg()
    mesh = _mesh((4, 2), ("dp", "tp"))
    build, shard_batch = make_finetune_step(cfg, mesh, optax.adam(1e-2))
    step, params, opt_state = build(
        init_params(jax.random.PRNGKey(0), cfg))
    rng = np.random.RandomState(0)
    batch = shard_batch(_batch(rng, 8, 16))
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses  # it learns the batch


def test_bert_mlm_objective_trains(hvd_world):
    cfg = _cfg()
    mesh = _mesh((4, 2), ("dp", "tp"))
    build, shard_batch = make_finetune_step(
        cfg, mesh, optax.adam(1e-2), objective="mlm")
    step, params, opt_state = build(
        init_params(jax.random.PRNGKey(1), cfg))
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, VOCAB, size=(8, 16)).astype(np.int32)
    mlm_mask = (rng.rand(8, 16) < 0.15).astype(np.int32)
    mlm_mask[:, 0] = 1  # at least one target per row
    batch = shard_batch({"tokens": tokens, "targets": tokens,
                         "mlm_mask": mlm_mask})
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_bert_sharded_matches_single_device(hvd_world):
    """(dp=4, tp=2) loss and gradient norm == the (1, 1) mesh values:
    vocab-parallel embedding/MLM head and the tp column/row split must
    be exact re-shardings, not approximations."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, VOCAB, size=(4, 16)).astype(np.int32)
    # UNEVEN mask counts per row (realistic ~15% masking): the global
    # masked mean must not depend on how rows land on dp shards.
    mlm_mask = (rng.rand(4, 16) < 0.3).astype(np.int32)
    mlm_mask[:, 0] = 1  # at least one target per row
    batch = {"tokens": tokens, "targets": tokens, "mlm_mask": mlm_mask}

    def loss_and_gradnorm(mesh):
        bspec = {"tokens": P("dp", None), "targets": P("dp", None),
                 "mlm_mask": P("dp", None)}
        # check_vma=True: the vma-tracked AD differentiates the dp
        # pmean with exact collective transposes, so per-shard grads
        # ARE the global-batch gradient — the property the fine-tune
        # step relies on.
        f = jax.jit(jax.shard_map(
            jax.value_and_grad(lambda p, b: mlm_loss(p, b, cfg)),
            mesh=mesh, in_specs=(param_specs(cfg), bspec),
            out_specs=(P(), param_specs(cfg)), check_vma=True))
        loss, g = f(params, batch)
        return float(loss), float(optax.global_norm(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), g)))

    l1, g1 = loss_and_gradnorm(
        Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "tp")))
    l8, g8 = loss_and_gradnorm(_mesh((4, 2), ("dp", "tp")))
    np.testing.assert_allclose(l8, l1, rtol=1e-5)
    np.testing.assert_allclose(g8, g1, rtol=1e-4)


def test_bert_padding_mask_matches_truncation(hvd_world):
    """Padding keys must be invisible: encoding [x | pad] with the
    mask gives the same prefix hidden states as encoding x alone."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(3)
    s, pad = 8, 8
    tokens = rng.randint(0, VOCAB, size=(2, s)).astype(np.int32)
    padded = np.concatenate(
        [tokens, np.zeros((2, pad), np.int32)], axis=1)
    mask = np.concatenate(
        [np.ones((2, s), np.int32), np.zeros((2, pad), np.int32)],
        axis=1)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                ("dp", "tp"))

    def run(toks, m):
        f = jax.jit(jax.shard_map(
            lambda p, t, mm: encode(p, t, cfg, None, mm),
            mesh=mesh,
            in_specs=(param_specs(cfg), P("dp", None),
                      (P("dp", None) if m is not None else None)),
            out_specs=P("dp", None, None), check_vma=False))
        return np.asarray(f(params, toks, m))

    full = run(padded, mask)
    # Position embeddings differ beyond s only for the PAD region;
    # compare the valid prefix against the truncated encoding.
    short = run(tokens, np.ones((2, s), np.int32))
    np.testing.assert_allclose(full[:, :s], short, rtol=2e-4,
                               atol=2e-5)
