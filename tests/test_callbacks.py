"""Callback tests (reference: the Keras callback tests in
test/parallel/test_tensorflow2_keras.py, framework-free here)."""

import numpy as np
import pytest

from horovod_tpu.jax.callbacks import (BroadcastGlobalVariablesCallback,
                                       LearningRateScheduleCallback,
                                       LearningRateWarmupCallback,
                                       MetricAverageCallback)


def test_warmup_ramp():
    cb = LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=4,
                                    multiplier=8.0)
    assert cb.lr_at(0) == pytest.approx(0.1)
    assert cb.lr_at(4) == pytest.approx(0.8)
    assert cb.lr_at(10) == pytest.approx(0.8)
    # Monotone ramp in between.
    assert 0.1 < cb.lr_at(2) < 0.8
    # Batch hook tracks fractional epochs.
    cb.steps_per_epoch = 10
    cb.on_epoch_begin(1)
    cb.on_batch_end(5, logs={})
    assert cb.current_lr == pytest.approx(cb.lr_at(1.5))


def test_warmup_optax_schedule_is_traceable():
    import jax
    cb = LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=2,
                                    steps_per_epoch=10, multiplier=4.0)
    sched = cb.as_optax_schedule()
    lrs = jax.jit(sched)(jax.numpy.arange(30))
    np.testing.assert_allclose(float(lrs[0]), 0.1, rtol=1e-5)
    np.testing.assert_allclose(float(lrs[-1]), 0.4, rtol=1e-5)


def test_schedule_callback():
    cb = LearningRateScheduleCallback(initial_lr=0.1, multiplier=0.5,
                                      start_epoch=2, end_epoch=5)
    cb.on_epoch_begin(0)
    assert cb.current_lr == pytest.approx(0.1)
    cb.on_epoch_begin(3)
    assert cb.current_lr == pytest.approx(0.05)
    cb.on_epoch_begin(7)  # outside window: keeps last value
    assert cb.current_lr == pytest.approx(0.05)
    cb2 = LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e, start_epoch=0)
    cb2.on_epoch_begin(2)
    assert cb2.current_lr == pytest.approx(0.01)


def test_broadcast_and_metric_average_inprocess(hvd_world):
    import jax.numpy as jnp
    cb = BroadcastGlobalVariablesCallback(root_rank=0)
    params = {"w": jnp.ones((4, 4))}
    out = cb.on_train_begin(params)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    assert cb.broadcast_done
    logs = {"loss": 1.5}
    out = MetricAverageCallback().on_epoch_end(0, logs)
    assert out["loss"] == pytest.approx(1.5)
