"""Latency-hiding collective matmul tests (8-device CPU world)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.collective_matmul import (all_gather_matmul,
                                                    matmul_reduce_scatter)


def _mesh():
    return Mesh(np.asarray(jax.devices()), ("tp",))


def test_all_gather_matmul_exact():
    n = len(jax.devices())
    m_loc, k, n_out = 4, 16, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n * m_loc, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n_out), jnp.float32)

    f = jax.jit(jax.shard_map(
        lambda xs, ws: all_gather_matmul(xs, ws, "tp"),
        mesh=_mesh(), in_specs=(P("tp", None), P(None, None)),
        out_specs=P(), check_vma=False))
    got = f(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               atol=1e-4, rtol=1e-5)


def test_all_gather_matmul_col_sharded_weight():
    n = len(jax.devices())
    m_loc, k, n_out = 2, 8, 8 * n
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n * m_loc, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n_out), jnp.float32)

    f = jax.jit(jax.shard_map(
        lambda xs, ws: all_gather_matmul(xs, ws, "tp"),
        mesh=_mesh(), in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp"), check_vma=False))
    got = f(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               atol=1e-4, rtol=1e-5)


def test_matmul_reduce_scatter_exact():
    n = len(jax.devices())
    m, k, n_out = 8 * n, 16 * n, 8
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n_out), jnp.float32)

    # x col-sharded, w row-sharded: partial products summed over tp,
    # rows scattered — the classic row-parallel linear layer
    f = jax.jit(jax.shard_map(
        lambda xs, ws: matmul_reduce_scatter(xs, ws, "tp"),
        mesh=_mesh(), in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None), check_vma=False))
    got = f(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               atol=1e-3, rtol=1e-4)


def test_matmul_reduce_scatter_rejects_ragged():
    n = len(jax.devices())
    with pytest.raises(ValueError):
        jax.jit(jax.shard_map(
            lambda xs, ws: matmul_reduce_scatter(xs, ws, "tp"),
            mesh=_mesh(), in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None), check_vma=False))(
                jnp.ones((n + 1, n * 2)), jnp.ones((2, 4)))
