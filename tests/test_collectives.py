"""Collective matrix tests: every op x dtype x process-set, async handles,
fusion, error propagation (reference: test/parallel/test_torch.py /
test_tensorflow.py collective cases)."""

import numpy as np
import pytest

import horovod_tpu as hvd

SIZE = 8
DTYPES = [np.float32, np.int32, "bfloat16"]


def _stacked(shape, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(SIZE, *shape)
    if dtype == "bfloat16":
        import jax.numpy as jnp
        return jnp.asarray(x, dtype=jnp.bfloat16)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return (x * 10).astype(dtype)
    return x.astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
def test_allreduce_sum(hvd_world, dtype):
    x = _stacked((4, 3), dtype)
    out = hvd.allreduce(x, op=hvd.Sum)
    expected = np.sum(np.asarray(x, dtype=np.float64), axis=0)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float64), expected,
                               rtol=1e-2 if dtype == "bfloat16" else 1e-5)


def test_allreduce_average(hvd_world):
    x = _stacked((5,))
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(out, x.mean(axis=0), rtol=1e-5)


def test_allreduce_average_legacy_kwarg(hvd_world):
    x = _stacked((5,))
    out = hvd.allreduce(x, average=True)
    np.testing.assert_allclose(out, x.mean(axis=0), rtol=1e-5)
    with pytest.raises(ValueError):
        hvd.allreduce(x, average=True, op=hvd.Sum)


@pytest.mark.parametrize("op,npfn", [(hvd.Min, np.min), (hvd.Max, np.max),
                                     (hvd.Product, np.prod)])
def test_allreduce_min_max_product(hvd_world, op, npfn):
    x = _stacked((3, 2))
    out = hvd.allreduce(x, op=op)
    np.testing.assert_allclose(out, npfn(x, axis=0), rtol=1e-4)


def test_allreduce_prescale_postscale(hvd_world):
    x = _stacked((4,))
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5,
                        postscale_factor=2.0)
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5)


def test_allreduce_adasum(hvd_world):
    x = _stacked((16,))
    out = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    assert out.shape == (16,)
    assert np.all(np.isfinite(out))
    # Adasum of identical tensors collapses toward a single copy:
    same = np.tile(np.arange(8.0, dtype=np.float32), (SIZE, 1))
    merged = np.asarray(hvd.allreduce(same, op=hvd.Adasum))
    np.testing.assert_allclose(merged, same[0], rtol=1e-4)


def test_allreduce_async_poll_synchronize(hvd_world):
    x = _stacked((1000,))
    h = hvd.allreduce_async(x, op=hvd.Sum, name="big")
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-4)


def test_grouped_allreduce_fusion(hvd_world):
    tensors = [_stacked((n,), seed=n) for n in (3, 5, 7, 1024)]
    outs = hvd.grouped_allreduce(tensors, op=hvd.Sum, name="grp")
    for t, o in zip(tensors, outs):
        np.testing.assert_allclose(o, t.sum(axis=0), rtol=1e-4)


def test_grouped_allgather_and_reducescatter(hvd_world):
    # Grouped variants (reference v0.28): atomic groups in the
    # in-process stacked-input mode.
    a = _stacked((2, 3), seed=1)
    b = _stacked((4,), seed=2)
    ga, gb = hvd.grouped_allgather([a, b], name="gag")
    np.testing.assert_allclose(ga, a.reshape(SIZE * 2, 3), rtol=1e-6)
    np.testing.assert_allclose(gb, b.reshape(SIZE * 4), rtol=1e-6)
    c = _stacked((SIZE * 2,), seed=3)
    d = _stacked((SIZE,), seed=4)
    rc, rd = hvd.grouped_reducescatter([c, d], op=hvd.Sum, name="grs")
    np.testing.assert_allclose(
        np.concatenate([np.asarray(x) for x in rc]),
        c.sum(axis=0), rtol=1e-4)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(x) for x in rd]),
        d.sum(axis=0), rtol=1e-4)


def test_allgather_uniform(hvd_world):
    x = _stacked((2, 3))
    out = hvd.allgather(x)
    np.testing.assert_allclose(out, x.reshape(SIZE * 2, 3), rtol=1e-6)


def test_allgather_ragged(hvd_world):
    per_rank = [np.full((r + 1, 2), r, dtype=np.float32) for r in range(SIZE)]
    out = np.asarray(hvd.allgather(per_rank))
    assert out.shape == (sum(r + 1 for r in range(SIZE)), 2)
    np.testing.assert_allclose(out, np.concatenate(per_rank, axis=0))


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(hvd_world, root):
    x = _stacked((4, 2))
    out = hvd.broadcast(x, root_rank=root)
    np.testing.assert_allclose(out, x[root], rtol=1e-6)


def test_alltoall_uniform(hvd_world):
    # rank r sends value r*SIZE+j to rank j.
    x = np.arange(SIZE * SIZE, dtype=np.float32).reshape(SIZE, SIZE)
    out = np.asarray(hvd.alltoall(x))
    expected = x.T.reshape(SIZE, SIZE)
    np.testing.assert_allclose(out, expected)


def test_alltoall_ragged_splits(hvd_world):
    # rank r sends (j+1) rows to rank j.
    splits = np.tile(np.arange(1, SIZE + 1), (SIZE, 1))
    rows = splits[0].sum()
    x = np.stack([np.full((rows, 2), r, dtype=np.float32)
                  for r in range(SIZE)])
    # Twice with the same splits: the first call takes the eager
    # reassembly, the repeat takes the compiled device-all_to_all
    # program — both must agree.
    for _ in range(2):
        out, recv_splits = hvd.alltoall(x, splits=splits)
        np.testing.assert_array_equal(recv_splits, splits.T)
        # rank j receives (j+1) rows from each rank, in rank order.
        for j in range(SIZE):
            got = np.asarray(out[j])
            assert got.shape == ((j + 1) * SIZE, 2)
            expected = np.repeat(np.arange(SIZE, dtype=np.float32),
                                 j + 1)
            np.testing.assert_allclose(got[:, 0], expected)


def test_reducescatter(hvd_world):
    x = _stacked((SIZE * 3, 2))
    out = np.asarray(hvd.reducescatter(x, op=hvd.Sum))
    full = x.sum(axis=0)
    for r in range(SIZE):
        np.testing.assert_allclose(out[r], full[r * 3:(r + 1) * 3],
                                   rtol=1e-4)


def test_barrier_and_join(hvd_world):
    hvd.barrier()
    assert hvd.join() == SIZE - 1


def test_reducescatter_uneven(hvd_world):
    # 11 rows over 8 ranks: chunks [2,2,2,1,1,1,1,1] — the native core's
    # layout (operations.cc REDUCESCATTER: rank j gets d0//n + (1 if
    # j < d0%n) rows, earlier ranks larger).  Integer-valued floats so
    # any summation order gives the exact same bits as the core's ring.
    d0 = 11
    x = np.arange(SIZE * d0 * 2, dtype=np.float32).reshape(SIZE, d0, 2)
    out = hvd.reducescatter(x, op=hvd.Sum)
    full = x.sum(axis=0)
    base, rem = divmod(d0, SIZE)
    off = 0
    assert len(out) == SIZE
    for j in range(SIZE):
        c = base + (1 if j < rem else 0)
        np.testing.assert_array_equal(np.asarray(out[j]),
                                      full[off:off + c])
        off += c

    # Average divides by the full world count, like the core.
    out = hvd.reducescatter(x, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out[0]),
                               full[:2] / SIZE, rtol=1e-6)


def test_reducescatter_rejects_adasum(hvd_world):
    # Adasum is allreduce-only; both even and uneven row counts must
    # reject identically (not silently fall back to Sum).
    for rows in (SIZE * 2, SIZE + 3):
        with pytest.raises(ValueError, match="allreduce-only"):
            hvd.reducescatter(_stacked((rows, 2)), op=hvd.Adasum)


@pytest.mark.parametrize("op,npfn", [(hvd.Min, np.min), (hvd.Max, np.max),
                                     (hvd.Product, np.prod)])
def test_reducescatter_min_max_product(hvd_world, op, npfn):
    # r2 edge closed: the scatter-less reduce ops reduce fully and
    # slice each rank's chunk (even and uneven row counts).
    x = _stacked((SIZE * 2, 3), seed=7)
    out = np.asarray(hvd.reducescatter(x, op=op))  # [size, 2, 3]
    np.testing.assert_allclose(out.reshape(SIZE * 2, 3),
                               npfn(x, axis=0), rtol=1e-4)
    d0 = SIZE + 3  # uneven
    x = _stacked((d0, 2), seed=8)
    out = hvd.reducescatter(x, op=op)
    full = npfn(x, axis=0)
    rows = [d0 // SIZE + (1 if j < d0 % SIZE else 0)
            for j in range(SIZE)]
    off = 0
    for j in range(SIZE):
        np.testing.assert_allclose(np.asarray(out[j]),
                                   full[off:off + rows[j]], rtol=1e-4)
        off += rows[j]


def test_join_zero_contribution(hvd_world):
    # Ranks 2 and 5 are out of data: their rows contribute zeros to Sum;
    # Average divides by the LIVE contributor count (zero is not
    # Average's identity — a full-world divisor would bias toward zero).
    x = np.ones((SIZE, 4), np.float32) * (np.arange(SIZE, dtype=np.float32)
                                          + 1.0)[:, None]
    assert hvd.join(ranks=[2, 5]) == -1
    live = x.copy()
    live[[2, 5]] = 0.0
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out), live.sum(axis=0))
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out),
                               live.sum(axis=0) / (SIZE - 2), rtol=1e-6)

    # Fused path: several small allreduces in one cycle, still zeroed.
    hs = [hvd.allreduce_async(x, name="join_f%d" % i, op=hvd.Sum)
          for i in range(3)]
    for h in hs:
        np.testing.assert_array_equal(np.asarray(h.wait()),
                                      live.sum(axis=0))

    # Non-allreduce collectives are rejected while ranks are joined
    # (mirrors the controller's multihost rule), as is Adasum (zero is
    # not a neutral element for its dot-product combine).
    with pytest.raises(Exception, match="joined"):
        hvd.allgather(x)
    with pytest.raises(Exception, match="joined"):
        hvd.allreduce(x, op=hvd.Adasum)

    # Min/Max/Product likewise: zero is not their reduction identity, so
    # a zero contribution would silently corrupt the result (e.g.
    # Min over positives returning 0) — reject loudly instead.
    for bad_op in (hvd.Min, hvd.Max, hvd.Product):
        with pytest.raises(Exception, match="joined"):
            hvd.allreduce(x, op=bad_op)

    # Finalize: remaining ranks join in rank order; last is rank 7.
    assert hvd.join() == SIZE - 1

    # The joined set cleared: full-world allreduce again.
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out), x.sum(axis=0))


def test_join_not_retroactive(hvd_world):
    # join() marks are snapshot at ENQUEUE: an allreduce submitted while
    # every rank was in-data keeps rank 4's contribution even if rank 4
    # joins before the background cycle executes it.
    x = np.ones((SIZE, 3), np.float32)
    h = hvd.allreduce_async(x, name="pre_join", op=hvd.Sum)
    hvd.join(ranks=[4])
    np.testing.assert_array_equal(np.asarray(h.wait()),
                                  np.full(3, float(SIZE), np.float32))
    # ...and one enqueued after the mark drops it.
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full(3, float(SIZE - 1), np.float32))
    assert hvd.join() == SIZE - 1


def test_process_set_collective(hvd_world):
    ps = hvd.add_process_set([0, 2, 4])
    x = np.ones((3, 5), dtype=np.float32) * np.arange(3)[:, None]
    out = hvd.allreduce(x, op=hvd.Sum, process_set=ps)
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5)
    g = hvd.allgather([x[i] for i in range(3)], process_set=ps)
    assert np.asarray(g).shape == (15,)
    hvd.remove_process_set(ps)


def test_shape_mismatch_error_propagates(hvd_world):
    with pytest.raises(ValueError):
        hvd.allreduce(np.ones((3, 2), dtype=np.float32))  # wrong world dim


def test_executable_cache_hits(hvd_world):
    from horovod_tpu.common import basics
    eng = basics._get_engine()
    x = _stacked((64,))
    hvd.allreduce(x, op=hvd.Sum, name="c1")
    misses = eng.cache.misses
    for _ in range(3):
        hvd.allreduce(x, op=hvd.Sum, name="c1")
    assert eng.cache.misses == misses  # steady state: no recompiles
    assert eng.cache.hits > 0


def test_timeline_written(tmp_path):
    import json
    hvd.shutdown()
    path = str(tmp_path / "tl.json")
    import os
    os.environ["HOROVOD_TIMELINE"] = path
    try:
        hvd.init()
        hvd.allreduce(_stacked((8,)), name="tltensor")
        hvd.shutdown()
    finally:
        os.environ.pop("HOROVOD_TIMELINE", None)
    events = json.load(open(path))
    names = {e.get("name") for e in events}
    assert any(n and n.startswith("NEGOTIATE") for n in names)
    assert any(n and n.startswith("EXEC") for n in names)
    assert all("ts" in e for e in events)


def test_multihost_adasum_combine_matches_host_tree():
    """Device-plane Adasum (ppermute XOR-tree, ops/multihost.py) must
    reproduce the host recursive-halving tree on every shard of an
    8-device mesh — the oracle the multihost executor relies on."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.ops.multihost import adasum_combine
    from horovod_tpu.utils.adasum import adasum_reduce_stacked

    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs), ("proc",))
    rng = np.random.RandomState(42)
    stacked = rng.randn(8, 33).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: adasum_combine(x[0], "proc", 8)[None],
        mesh=mesh, in_specs=(P("proc"),), out_specs=P("proc"),
        check_vma=False))
    out = np.asarray(fn(stacked))
    oracle = np.asarray(adasum_reduce_stacked(stacked))
    for r in range(8):  # every shard converges to the tree result
        np.testing.assert_allclose(out[r], oracle, rtol=1e-5, atol=1e-6)


def test_multihost_adasum_combine_rejects_non_pow2():
    from horovod_tpu.ops.engine import HorovodInternalError
    from horovod_tpu.ops.multihost import adasum_combine
    import jax.numpy as jnp
    with pytest.raises(HorovodInternalError, match="power-of-two"):
        adasum_combine(jnp.ones((4,)), "proc", 6)
