"""Unit tests for the r12 cross-host wire-compression plane: the
quantizing codecs (int8 absmax / fp8 e4m3), the error-feedback wrapper
(convergence on a deterministic toy where plain quantization provably
stalls), the `_CastCompressor` integer no-op regression, and the codec
resolution / env parsing seams.  The 2-proc hier e2e with the wire-byte
accounting assertions lives in test_multihost.py (slow-marked)."""

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.jax.compression import (FP8_WIRE_DTYPE, Compression,
                                         ErrorFeedback, FP8Compressor,
                                         FP16Compressor, Int8Quantizer,
                                         ScaledFP8Quantizer)


def test_int8_roundtrip_error_bound():
    # Symmetric absmax quantization: |x - deq(q(x))| <= scale/2
    # elementwise, scale = absmax/127.
    rng = np.random.RandomState(7)
    for shape in ((513,), (4, 1024), (3, 7, 11)):
        x = jnp.asarray(rng.randn(*shape).astype(np.float32) * 5.0)
        q, ctx = Int8Quantizer.compress(x)
        assert q.dtype == jnp.int8
        scale, dtype = ctx
        assert dtype == x.dtype
        d = Int8Quantizer.decompress(q, ctx)
        assert d.dtype == x.dtype
        bound = np.broadcast_to(np.asarray(scale), shape) / 2 + 1e-6
        assert np.all(np.abs(np.asarray(d) - np.asarray(x)) <= bound)


def test_int8_per_chunk_scales():
    # Rows of the leading axis are independent chunks: a tiny row next
    # to a huge row keeps its own absmax, so its error bound is its OWN
    # scale/2 — a global scale would wipe it out entirely.
    x = jnp.asarray(np.stack([
        np.linspace(-1e-3, 1e-3, 256),
        np.linspace(-1e3, 1e3, 256)]).astype(np.float32))
    q, (scale, _) = Int8Quantizer.compress(x)
    assert scale.shape == (2, 1)
    d = np.asarray(Int8Quantizer.decompress(q, (scale, x.dtype)))
    assert np.max(np.abs(d[0] - np.asarray(x)[0])) <= 1e-3 / 254 + 1e-9
    # With one global scale the small row would have quantized to all
    # zeros (1e-3 << 1e3/254); per-chunk it round-trips.
    assert np.any(np.asarray(q)[0] != 0)


def test_int8_all_zero_chunk_roundtrips():
    x = jnp.zeros((3, 64), jnp.float32)
    q, ctx = Int8Quantizer.compress(x)
    np.testing.assert_array_equal(
        np.asarray(Int8Quantizer.decompress(q, ctx)), 0.0)


def test_int8_integer_passthrough_identity():
    x = jnp.arange(32, dtype=jnp.int32)
    q, ctx = Int8Quantizer.compress(x)
    assert ctx is None and q is x
    assert Int8Quantizer.decompress(q, ctx) is x


@pytest.mark.skipif(FP8_WIRE_DTYPE is None,
                    reason="this jax has no float8_e4m3fn")
def test_fp8_roundtrip_error_bound():
    # e4m3: 3 mantissa bits -> relative error <= 2^-4 for values well
    # inside the (+-448) range.
    x = jnp.asarray(np.linspace(-100.0, 100.0, 1001,
                                dtype=np.float32))
    w, ctx = FP8Compressor.compress(x)
    assert w.dtype == FP8_WIRE_DTYPE
    d = np.asarray(FP8Compressor.decompress(w, ctx))
    rel = np.abs(d - np.asarray(x)) / np.maximum(np.abs(np.asarray(x)),
                                                 1e-6)
    assert np.max(rel[np.abs(np.asarray(x)) > 1e-3]) <= 2 ** -4 + 1e-6


@pytest.mark.skipif(FP8_WIRE_DTYPE is None,
                    reason="this jax has no float8_e4m3fn")
def test_scaled_fp8_is_range_safe_where_plain_cast_nans():
    # e4m3's finite range ends at +-448 and astype past it yields NaN;
    # the ENGINE's fp8 wire (ScaledFP8Quantizer) absmax-scales into
    # range, so reduced values of any magnitude survive both wire legs.
    x = jnp.asarray(np.linspace(-5000.0, 5000.0, 513,
                                dtype=np.float32))
    assert np.isnan(np.asarray(
        FP8Compressor.compress(x)[0], dtype=np.float32)).any()
    q, ctx = ScaledFP8Quantizer.compress(x)
    d = np.asarray(ScaledFP8Quantizer.decompress(q, ctx))
    assert np.all(np.isfinite(d))
    # Bounded relative error (3 mantissa bits -> <= 2^-4) plus the
    # scaled absolute floor near zero.
    assert np.all(np.abs(d - np.asarray(x))
                  <= np.abs(np.asarray(x)) * 0.07 + 2.0)


def test_error_feedback_preserves_payload_dtype():
    # The f32 lift inside ErrorFeedback must not leak: decompress
    # returns the CALLER's dtype (bf16 in, bf16 out), like the bare
    # compressors do.
    for comp in (Int8Quantizer, FP16Compressor):
        ef = ErrorFeedback(comp)
        x = jnp.linspace(-1.0, 1.0, 16).astype(jnp.bfloat16)
        w, ctx = ef.compress(x, bucket="dt")
        assert ef.decompress(w, ctx).dtype == jnp.bfloat16


def test_cast_compressor_integer_noop_regression():
    # The pre-r12 bug: integer tensors passed through with ctx set to
    # their dtype, so decompress re-cast (a silent copy) instead of
    # being a true identity.  ctx must be None and decompress must
    # return the SAME object.
    x = jnp.arange(16, dtype=jnp.int64)
    w, ctx = FP16Compressor.compress(x)
    assert ctx is None
    assert w is x
    assert FP16Compressor.decompress(w, ctx) is x
    # Floating tensors still cast + restore.
    f = jnp.ones((4,), jnp.float32)
    w, ctx = FP16Compressor.compress(f)
    assert w.dtype == jnp.float16 and ctx == jnp.float32
    assert FP16Compressor.decompress(w, ctx).dtype == jnp.float32


def test_compression_namespace_exports():
    assert Compression.int8 is Int8Quantizer
    assert Compression.fp8 is FP8Compressor


def test_quantizers_rejected_by_summing_brackets():
    # The framework bracket (compress -> allreduce of the wire tensor
    # -> decompress) sums wire tensors across ranks: int8 addition
    # wraps and per-rank scales diverge, so handing it a quantizing
    # codec must fail LOUDLY before any collective runs — the engine
    # env (HOROVOD_CROSS_HOST_COMPRESSION) is the quantized-reduction
    # path.
    from horovod_tpu.jax.optimizer import allreduce_gradients
    from horovod_tpu.jax.spmd import allreduce as spmd_allreduce
    for codec in (Compression.int8, Compression.fp8):
        with pytest.raises(ValueError,
                           match="HOROVOD_CROSS_HOST_COMPRESSION"):
            allreduce_gradients({"g": jnp.ones((4,))},
                                compression=codec)
        with pytest.raises(ValueError,
                           match="HOROVOD_CROSS_HOST_COMPRESSION"):
            spmd_allreduce(jnp.ones((4,)), compression=codec)
    # The cast compressors stay accepted (reduce-safe by construction;
    # outside any mesh axis the call fails later on the axis, not on
    # the codec).
    from horovod_tpu.jax.compression import check_reduce_safe
    check_reduce_safe(Compression.fp16, "test")
    check_reduce_safe(Compression.bf16, "test")
    check_reduce_safe(Compression.none, "test")
    # An ErrorFeedback WRAPPER is exactly as safe as its wrapped wire:
    # EF(int8) must be rejected (residuals don't stop int8 addition
    # from wrapping), EF(fp16) accepted.
    with pytest.raises(ValueError,
                       match="HOROVOD_CROSS_HOST_COMPRESSION"):
        check_reduce_safe(ErrorFeedback(Int8Quantizer), "test")
    check_reduce_safe(ErrorFeedback(FP16Compressor), "test")


def test_error_feedback_recovers_quadratic_optimum_plain_int8_stalls():
    # Deterministic 2-worker data-parallel toy: worker gradients are
    # g_i = +-b + (w - c)/2 with a large pin component keeping BOTH
    # workers' absmax (and so the int8 scale) constant.  The true
    # summed gradient is (w - c): plain per-worker quantization rounds
    # the useful signal away EXACTLY (|g/2| < scale/2, b on the quant
    # grid), so w NEVER moves; error feedback accumulates the signal
    # in the residual until it crosses a quantization step — driving w
    # to the fp32 optimum.  No randomness anywhere: the contrast is
    # exact, not statistical.
    d = 16
    c = np.linspace(0.02, 0.08, d).astype(np.float32)     # optimum
    pin = np.float32(12.7)                                 # scale 0.1
    b = np.full(d, 6.0, np.float32)                        # on-grid
    lr = 0.02
    steps = 400

    def run(use_ef):
        efs = [ErrorFeedback(Int8Quantizer) for _ in range(2)]
        w = np.zeros(d, np.float32)
        for _ in range(steps):
            g = (w - c) / 2.0
            total = np.zeros(d, np.float32)
            for i, sign in enumerate((1.0, -1.0)):
                vec = jnp.asarray(np.concatenate(
                    [[pin], sign * b + g]).astype(np.float32))
                if use_ef:
                    q, ctx = efs[i].compress(vec, bucket="g")
                else:
                    q, ctx = Int8Quantizer.compress(vec)
                deq = np.asarray(Int8Quantizer.decompress(q, ctx))
                total += deq[1:]
            w = w - lr * total
        return w

    w_plain = run(use_ef=False)
    w_ef = run(use_ef=True)
    # Plain int8: the stall is exact — not one step moved the weights.
    np.testing.assert_array_equal(w_plain, np.zeros(d, np.float32))
    # Error feedback: at the fp32 optimum within the EF offset bound
    # (lr * residual cap), far inside the plain error.
    assert np.max(np.abs(w_ef - c)) < 0.01, np.max(np.abs(w_ef - c))
    assert np.max(np.abs(w_ef - c)) < 0.2 * np.max(np.abs(w_plain - c))


def test_error_feedback_residual_telescopes():
    # sum_t sent_t = T*x + res_0 - res_T: the mean of T compressed
    # steps of a CONSTANT tensor converges on the tensor itself.
    x = jnp.asarray(np.linspace(-1.0, 1.0, 512).astype(np.float32))
    ef = ErrorFeedback(Int8Quantizer)
    T = 32
    acc = np.zeros(512, np.float64)
    for _ in range(T):
        q, ctx = ef.compress(x, bucket="t")
        acc += np.asarray(ef.decompress(q, ctx), dtype=np.float64)
    single_step_bound = 1.0 / 254.0
    assert np.max(np.abs(acc / T - np.asarray(x))) < \
        2 * single_step_bound / T + 1e-7


def test_error_feedback_bucket_lru_cap():
    ef = ErrorFeedback(Int8Quantizer, max_buckets=3)
    for i in range(8):
        ef.compress(jnp.ones((4,), jnp.float32) * (i + 1),
                    bucket=("b", i))
    assert len(ef._residuals) == 3
    assert ("b", 7) in ef._residuals and ("b", 0) not in ef._residuals
    ef.reset()
    assert not ef._residuals


def test_error_feedback_integer_passthrough_keeps_no_residual():
    ef = ErrorFeedback(Int8Quantizer)
    x = jnp.arange(8, dtype=jnp.int32)
    q, ctx = ef.compress(x, bucket="i")
    assert ctx is None and not ef._residuals


def test_parse_compression_env():
    from horovod_tpu.common.config import _parse_compression
    assert _parse_compression(None) == "none"
    assert _parse_compression("INT8") == "int8"
    assert _parse_compression("bfloat16") == "bf16"
    assert _parse_compression("fp8") == "fp8"
    with pytest.raises(ValueError, match="CROSS_HOST_COMPRESSION"):
        _parse_compression("int4")


def test_codec_resolution():
    from horovod_tpu.ops.multihost import _resolve_codec
    assert _resolve_codec("none") is None
    c = _resolve_codec("int8")
    assert (c.kind, c.wire.itemsize) == ("quant", 1)
    c = _resolve_codec("bf16")
    assert (c.kind, c.wire.itemsize) == ("cast", 2)
    with pytest.raises(ValueError):
        _resolve_codec("zfp")


def test_quant_codec_excludes_product():
    # An element below its chunk's absmax/254 quantizes to exactly 0
    # and zeroes a Product reduction — unbounded relative error, so
    # the quant codecs must route Product to the uncompressed plane
    # (the cast codecs keep it: bounded relative error).
    import types

    from horovod_tpu.ops.multihost import (PRODUCT, SUM,
                                           GlobalMeshCollectives,
                                           _resolve_codec)
    wc = GlobalMeshCollectives._wire_codec
    quant = types.SimpleNamespace(_codec=_resolve_codec("int8"))
    assert wc(quant, np.float32, PRODUCT) is None
    assert wc(quant, np.float32, SUM) is not None
    cast = types.SimpleNamespace(_codec=_resolve_codec("bf16"))
    assert wc(cast, np.float32, PRODUCT) is not None


def test_codec_fp8_fallback_is_loud(monkeypatch, caplog):
    # A jax without float8 dtypes must downgrade fp8 to a bf16 wire
    # with an ERROR log — never silently ship full precision.
    import logging

    from horovod_tpu.jax import compression as comp
    from horovod_tpu.ops import multihost as mh
    monkeypatch.setattr(comp, "FP8_WIRE_DTYPE", None)
    with caplog.at_level(logging.ERROR, logger="horovod_tpu"):
        c = mh._resolve_codec("fp8")
    assert c.kind == "cast" and c.wire.itemsize == 2
    assert any("fp8" in rec.message for rec in caplog.records)
