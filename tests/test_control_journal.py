"""HA control plane unit tests: write-ahead journal roundtrips,
torn/corrupt record recovery, snapshot compaction + fallback, leader
term fencing (split-brain), client endpoint failover, and warm-standby
promotion (docs/elastic.md §Control-plane HA)."""

import base64
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.common import atomicio, faultline, metrics
from horovod_tpu.runner import journal
from horovod_tpu.runner.http_client import RendezvousClient
from horovod_tpu.runner.http_server import (RendezvousServer,
                                            SECRET_HEADER, StandbyServer,
                                            TERM_HEADER, compute_digest)
from horovod_tpu.runner.services import AddressTable

SECRET = "unit-secret"


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    monkeypatch.delenv("HVD_TPU_FAULT", raising=False)
    monkeypatch.delenv("HOROVOD_RENDEZVOUS_ENDPOINTS", raising=False)
    faultline.reset()
    yield
    faultline.reset()


def _fast_rpc(monkeypatch, retries="1", backoff="0.01", deadline="3"):
    monkeypatch.setenv("HOROVOD_RPC_MAX_RETRIES", retries)
    monkeypatch.setenv("HOROVOD_RPC_RETRY_BACKOFF", backoff)
    monkeypatch.setenv("HOROVOD_RPC_DEADLINE", deadline)


def _dead_port() -> int:
    """A port with nothing listening (refused = transient, fast)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- journal roundtrips ----------------------------------------------------

def test_journal_roundtrip(tmp_path):
    d = str(tmp_path / "jnl")
    j = journal.ControlJournal(d)
    j.record_put("/a", b"1")
    j.record_put("/b", b"2")
    j.record_delete("/a")
    j.record_term(7)
    j.close()

    kv, term, seq = journal.replay(d)
    assert kv == {"/b": b"2"}
    assert term == 7
    assert seq == 4

    # Reopening resumes at the replayed sequence; appends continue it.
    j2 = journal.ControlJournal(d)
    assert (j2.state, j2.term, j2.seq) == (kv, 7, 4)
    assert j2.record_put("/c", b"3") == 5
    j2.close()
    kv2, _term2, seq2 = journal.replay(d)
    assert kv2 == {"/b": b"2", "/c": b"3"} and seq2 == 5


def test_snapshot_compaction_keeps_last_k(tmp_path, monkeypatch):
    monkeypatch.setattr(journal, "SNAPSHOT_EVERY", 4)
    d = str(tmp_path / "jnl")
    j = journal.ControlJournal(d)
    for i in range(20):
        j.record_put("/k%d" % i, b"v%d" % i)
    j.close()

    snaps = [n for n in os.listdir(d) if n.endswith(".snap")]
    segs = [n for n in os.listdir(d) if n.endswith(".walseg")]
    assert len(snaps) == journal.KEEP_SNAPSHOTS
    # Segments fully covered by the oldest retained snapshot are gone:
    # with snapshots every 4 records, at most a few live segments stay.
    assert len(segs) <= journal.KEEP_SNAPSHOTS + 1
    kv, _term, seq = journal.replay(d)
    assert seq == 20
    assert kv == {"/k%d" % i: b"v%d" % i for i in range(20)}


def test_parse_frames_resyncs_after_torn_record():
    f1 = atomicio.frame(journal.MAGIC, 1, json.dumps(
        {"op": "put", "k": "/a", "v": journal._b64(b"x")}).encode())
    f2 = atomicio.frame(journal.MAGIC, 2, json.dumps(
        {"op": "put", "k": "/b", "v": journal._b64(b"y")}).encode())
    f3 = atomicio.frame(journal.MAGIC, 3, json.dumps(
        {"op": "put", "k": "/c", "v": journal._b64(b"z")}).encode())
    torn = f2[:len(f2) - 7]  # mid-payload truncation
    skips = []
    before = metrics.series_sum("kv_journal_skipped_records_total")
    out = journal.parse_frames(f1 + torn + f3, on_skip=skips.append)
    assert [seq for seq, _f, _op in out] == [1, 3]
    assert skips  # loud
    assert metrics.series_sum("kv_journal_skipped_records_total") > before


def test_corrupt_crc_record_skipped_on_replay(tmp_path):
    d = str(tmp_path / "jnl")
    j = journal.ControlJournal(d)
    j.record_put("/a", b"aaaa")
    j.record_put("/b", b"bbbb")
    j.close()
    seg = [os.path.join(d, n) for n in os.listdir(d)
           if n.endswith(".walseg")][0]
    blob = bytearray(open(seg, "rb").read())
    # Flip one payload byte of the FIRST record (its CRC now fails);
    # the second record must survive the resync.
    blob[len(journal.MAGIC) + atomicio.HEADER.size + 2] ^= 0xFF
    open(seg, "wb").write(bytes(blob))

    before = metrics.series_sum("kv_journal_skipped_records_total")
    kv, _term, seq = journal.replay(d)
    assert "/a" not in kv and kv["/b"] == b"bbbb"
    assert seq == 2
    assert metrics.series_sum("kv_journal_skipped_records_total") > before


def test_journal_torn_write_fault_site(tmp_path, monkeypatch):
    # CI fault-smoke runs this node id: an injected torn append (the
    # power-loss-mid-fsync shape) costs exactly that record on replay.
    d = str(tmp_path / "jnl")
    monkeypatch.setenv("HVD_TPU_FAULT", "kv.journal.torn:drop@times=1")
    faultline.reset()
    j = journal.ControlJournal(d)
    j.record_put("/lost", b"torn-away")
    j.record_put("/kept", b"ok")
    j.close()
    faultline.reset()

    kv, _term, seq = journal.replay(d)
    assert "/lost" not in kv
    assert kv["/kept"] == b"ok"
    assert seq == 2


def test_snapshot_chain_falls_back_past_corrupt_newest(tmp_path,
                                                       monkeypatch):
    monkeypatch.setattr(journal, "SNAPSHOT_EVERY", 3)
    d = str(tmp_path / "jnl")
    j = journal.ControlJournal(d)
    for i in range(9):  # three snapshots
        j.record_put("/k%d" % i, b"v")
    j.close()
    snaps = sorted(n for n in os.listdir(d) if n.endswith(".snap"))
    assert len(snaps) >= 2
    # Corrupt the NEWEST snapshot: replay must fall back to the
    # previous one and re-apply the journal tail past it.
    open(os.path.join(d, snaps[-1]), "wb").write(b"garbage")
    kv, _term, seq = journal.replay(d)
    assert seq == 9
    assert set(kv) == {"/k%d" % i for i in range(9)}


# -- term fencing (split-brain) --------------------------------------------

def test_old_term_leader_fences_and_rejects_writes(tmp_path, monkeypatch):
    # Tiny deadline: a full-cycle 409 is retried (leaderless-window
    # ride-out) until the rpc deadline, and here it should raise fast.
    _fast_rpc(monkeypatch, retries="0", deadline="0.3")
    srv = RendezvousServer(host="127.0.0.1", secret=SECRET,
                           journal_dir=str(tmp_path / "jnl"))
    port = srv.start()
    addr = "127.0.0.1:%d" % port
    try:
        old = RendezvousClient(addr, SECRET)
        old.put("seed", "1")
        assert srv.term == 1 and not srv.fenced

        # A client that has seen a newer leader presents its term: the
        # stale leader fences itself and 409s — and the write is LOST
        # to this server, not silently forked.
        newer = RendezvousClient(addr, SECRET)
        newer._term = 2
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            newer.put("fork", "evil")
        assert exc_info.value.code == 409
        assert srv.fenced
        assert "/fork" not in srv.snapshot()

        # Fencing is sticky: even a termless client is rejected now.
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            old.put("late", "2")
        assert exc_info.value.code == 409
        # ... and the 409 response taught it the fenced server's term.
        assert old._term >= 1
    finally:
        srv.stop()


def test_client_rotates_to_live_leader_and_adopts_term(tmp_path,
                                                       monkeypatch):
    _fast_rpc(monkeypatch, retries="0")
    leader = RendezvousServer(host="127.0.0.1", secret=SECRET,
                              journal_dir=str(tmp_path / "jnl"))
    port = leader.start()
    leader.promote(3)
    dead = _dead_port()
    try:
        # First endpoint dead (transient exhaustion) -> rotate to the
        # live leader, pin it, and adopt its advertised term.
        cli = RendezvousClient("127.0.0.1:%d" % dead, SECRET,
                               endpoints=["127.0.0.1:%d" % port])
        cli.put("k", "v")
        assert cli.get("k") == "v"
        assert cli._term == 3
        assert cli._active == 1  # pinned past the dead endpoint
    finally:
        leader.stop()


def test_kv_server_die_drop_absorbed_by_retry(monkeypatch):
    # kv.server.die:drop = one synthetic 503; the client's transient
    # retry rides it out against the SAME endpoint.
    _fast_rpc(monkeypatch, retries="2")
    srv = RendezvousServer(host="127.0.0.1", secret=SECRET)
    port = srv.start()
    try:
        monkeypatch.setenv("HVD_TPU_FAULT", "kv.server.die:drop@times=1")
        faultline.reset()
        cli = RendezvousClient("127.0.0.1:%d" % port, SECRET)
        cli.put("k", "v")
        assert cli.get("k") == "v"
    finally:
        faultline.reset()
        srv.stop()


def test_get_blocking_rides_out_mid_poll_failover(monkeypatch):
    # The satellite-1 regression: get_blocking must re-resolve its
    # endpoint per poll iteration, not once at entry.  Entry resolves
    # while only the doomed endpoint answers; the key appears on the
    # OTHER endpoint after the first has died.
    _fast_rpc(monkeypatch, retries="0", deadline="1")
    a = RendezvousServer(host="127.0.0.1", secret=SECRET)
    b = RendezvousServer(host="127.0.0.1", secret=SECRET)
    pa, pb = a.start(), b.start()
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_ENDPOINTS",
                       "127.0.0.1:%d" % pb)
    cli = RendezvousClient("127.0.0.1:%d" % pa, SECRET)

    def _fail_over():
        time.sleep(0.4)
        a.stop()
        b.put_local("/ready", b"yes")

    t = threading.Thread(target=_fail_over, daemon=True)
    t.start()
    try:
        assert cli.get_blocking("ready", timeout=15.0) == "yes"
    finally:
        t.join()
        b.stop()


# -- warm standby ----------------------------------------------------------

def test_standby_tails_and_promotes_on_lease_expiry(tmp_path):
    leader = RendezvousServer(host="127.0.0.1", secret=SECRET,
                              journal_dir=str(tmp_path / "leader"))
    lport = leader.start()
    leader.put_local("/a", b"1")
    standby = StandbyServer("127.0.0.1:%d" % lport,
                            str(tmp_path / "standby"), secret=SECRET,
                            host="127.0.0.1", lease=0.6)
    failovers_before = metrics.series_sum("control_failovers_total")
    standby.start()
    try:
        # Bootstrap (dump) + tail replication of a post-bootstrap write.
        deadline = time.monotonic() + 10
        while standby.server.seq < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        leader.put_local("/b", b"2")
        while (standby.server.snapshot().get("/b") != b"2"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        pre_kill = leader.snapshot()
        assert standby.server.snapshot() == pre_kill
        assert not standby.promoted and standby.server.follower

        # Kill the leader: lease expiry promotes the standby with a
        # bumped term; its store is bitwise the pre-kill leader's.
        leader.stop()
        while not standby.promoted and time.monotonic() < deadline:
            time.sleep(0.05)
        assert standby.promoted
        assert standby.server.term == 2
        assert standby.server.snapshot() == pre_kill
        assert metrics.series_sum("control_failovers_total") \
            > failovers_before

        # The promoted standby serves writes under its own term.
        cli = RendezvousClient("127.0.0.1:%d" % standby.port, SECRET)
        cli.put("after", "failover")
        assert cli._term == 2
    finally:
        standby.stop()


def test_standby_partition_fault_site_drives_promotion(tmp_path,
                                                       monkeypatch):
    _fast_rpc(monkeypatch, retries="0", deadline="0.3")
    # Unbounded kv.standby.partition:drop = every poll lost: the lease
    # expires against a perfectly healthy leader and the standby
    # promotes — the split-brain HALF the term fence then contains.
    leader = RendezvousServer(host="127.0.0.1", secret=SECRET,
                              journal_dir=str(tmp_path / "leader"))
    lport = leader.start()
    monkeypatch.setenv("HVD_TPU_FAULT", "kv.standby.partition:drop")
    faultline.reset()
    standby = StandbyServer("127.0.0.1:%d" % lport,
                            str(tmp_path / "standby"), secret=SECRET,
                            host="127.0.0.1", lease=0.4)
    standby.start()
    try:
        deadline = time.monotonic() + 10
        while not standby.promoted and time.monotonic() < deadline:
            time.sleep(0.05)
        assert standby.promoted and standby.server.term >= 2
        # A client that learned the standby's term fences the old
        # leader on first contact: split brain lasts one request.
        cli = RendezvousClient("127.0.0.1:%d" % standby.port, SECRET)
        cli.put("x", "1")
        assert cli._term >= 2
        stale = RendezvousClient("127.0.0.1:%d" % lport, SECRET)
        stale._term = cli._term
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            stale.put("y", "2")
        assert exc_info.value.code == 409
        assert leader.fenced
        assert "/y" not in leader.snapshot()
    finally:
        faultline.reset()
        standby.stop()
        leader.stop()


# -- control endpoints -----------------------------------------------------

def test_control_status_and_dump_roundtrip(tmp_path):
    srv = RendezvousServer(host="127.0.0.1", secret=SECRET,
                           journal_dir=str(tmp_path / "jnl"))
    port = srv.start()
    try:
        srv.put_local("/k", b"\x00\x01binary")
        base = "http://127.0.0.1:%d" % port

        def authed_get(path):
            req = urllib.request.Request(base + path, headers={
                SECRET_HEADER: compute_digest(SECRET, path.encode())})
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.read(), dict(resp.headers)

        body, hdrs = authed_get("/control/status")
        doc = json.loads(body.decode())
        assert doc == {"term": 1, "seq": 1, "fenced": False,
                       "role": "leader"}
        assert hdrs[TERM_HEADER] == "1"

        body, _hdrs = authed_get("/control/dump")
        dump = json.loads(body.decode())
        assert base64.b64decode(dump["kv"]["/k"]) == b"\x00\x01binary"

        # Unauthenticated probes are refused (the dump carries state).
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(base + "/control/dump", timeout=5)
        assert exc_info.value.code == 403
    finally:
        srv.stop()


# -- notification address table --------------------------------------------

def test_address_table_register_wins_over_restore():
    t = AddressTable()
    t.restore(("h", 0), ("10.0.0.1", 1111))     # journal seed
    t.register(("h", 0), ("10.0.0.1", 2222))    # live re-registration
    assert t.get(("h", 0)) == ("10.0.0.1", 2222)
    # restore never overwrites a live entry...
    t.restore(("h", 0), ("10.0.0.1", 1111))
    assert t.get(("h", 0)) == ("10.0.0.1", 2222)
    # ...and two registrations for the same slot: latest wins.
    t.register(("h", 0), ("10.0.0.1", 3333))
    assert t.get(("h", 0)) == ("10.0.0.1", 3333)
    assert len(t) == 1


def test_address_table_evicts_stale_claim_on_same_address():
    # Reattach-after-failover: the address a dead slot held is reused
    # by a new registration — the stale entry must not shadow it.
    t = AddressTable()
    t.register(("h", 0), ("10.0.0.1", 5000))
    t.register(("h", 1), ("10.0.0.1", 5000))  # same socket, new owner
    assert t.get(("h", 1)) == ("10.0.0.1", 5000)
    assert t.get(("h", 0)) is None
    assert ("h", 0) not in t
    t.purge(("h", 1))
    assert len(t) == 0
