"""Elastic training tests.

Reference parity: ``test/integration/test_elastic_torch.py`` + the
elastic driver unit tests — discovery/registry/sampler/state units, and
real-process integration runs where a worker is killed mid-training
(failure → blacklist → resume from commit) and where the discovery
script's output is mutated mid-run (scale-up → re-rendezvous), with
multi-host faked as loopback-alias hosts on localhost.
"""

import os
import subprocess
import sys
import threading

from tests.utils.spawn import scaled_timeout
import time

import numpy as np
import pytest

from horovod_tpu.elastic.discovery import (FixedHosts, HostDiscoveryScript,
                                           HostManager, HostUpdateResult)
from horovod_tpu.elastic.registration import WorkerStateRegistry
from horovod_tpu.elastic.sampler import ElasticSampler
from horovod_tpu.elastic.state import ObjectState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- units -----------------------------------------------------------------

def test_discovery_script_parsing(tmp_path):
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\necho host1:4\necho '# comment'\n"
                      "echo host2\n")
    script.chmod(0o755)
    disc = HostDiscoveryScript(str(script), default_slots=2)
    assert disc.find_available_hosts_and_slots() == {
        "host1": 4, "host2": 2}


def test_discovery_script_failure(tmp_path):
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\nexit 7\n")
    script.chmod(0o755)
    with pytest.raises(RuntimeError):
        HostDiscoveryScript(str(script)).find_available_hosts_and_slots()


def test_host_manager_diffs_and_blacklist():
    registry = WorkerStateRegistry()
    hosts = {"a": 2, "b": 1}
    disc = FixedHosts(hosts)
    hm = HostManager(disc, registry.is_blacklisted)
    assert hm.update_available_hosts() == HostUpdateResult.ADDED
    assert hm.update_available_hosts() == HostUpdateResult.NO_UPDATE
    hosts["c"] = 1
    disc._hosts["c"] = 1
    assert hm.update_available_hosts() == HostUpdateResult.ADDED
    registry.record_failure("b")
    assert registry.is_blacklisted("b")
    assert hm.update_available_hosts() == HostUpdateResult.REMOVED
    assert "b" not in hm.current_hosts
    assert hm.ordered_slots(max_np=2) == [("a", 0), ("a", 1)]
    assert hm.ordered_slots() == [("a", 0), ("a", 1), ("c", 0)]


def test_worker_state_registry_threshold():
    reg = WorkerStateRegistry(failure_threshold=2)
    assert not reg.record_failure("h")
    assert not reg.is_blacklisted("h")
    assert reg.record_failure("h")
    assert reg.is_blacklisted("h")
    reg2 = WorkerStateRegistry()
    reg2.record_failure("x")
    assert reg2.blacklisted_hosts() == ["x"]


def test_worker_state_registry_cooldown_zero_is_permanent():
    # Satellite of the cooldown wiring: the default (0) must still mean
    # "blacklisted forever" (reference parity) — record_success clears
    # the failure streak but never lifts an active blacklist entry.
    reg = WorkerStateRegistry(failure_threshold=1, cooldown_secs=0.0)
    assert reg.record_failure("h")
    assert reg.is_blacklisted("h")
    time.sleep(0.05)
    assert reg.is_blacklisted("h")
    reg.record_success("h")
    assert reg.is_blacklisted("h")
    assert reg.cooldown_for("h") == 0.0


def test_worker_state_registry_cooldown_expiry_readmits():
    reg = WorkerStateRegistry(failure_threshold=1, cooldown_secs=0.1)
    assert reg.record_failure("h")
    assert reg.is_blacklisted("h")
    time.sleep(0.15)
    assert not reg.is_blacklisted("h")
    assert reg.blacklisted_hosts() == []
    # The failure streak reset with the expiry: the host must re-earn
    # the threshold before it is blacklisted again.
    reg2 = WorkerStateRegistry(failure_threshold=2, cooldown_secs=0.1)
    reg2.record_failure("h")
    assert reg2.record_failure("h")
    time.sleep(0.15)
    assert not reg2.is_blacklisted("h")
    assert not reg2.record_failure("h")  # 1/2 again, not 3/2


def test_worker_state_registry_reblacklist_doubles_cooldown():
    reg = WorkerStateRegistry(failure_threshold=1, cooldown_secs=10.0)
    assert reg.record_failure("h")
    assert reg.cooldown_for("h") == 10.0
    # Force expiry without sleeping: age the entry past the cooldown.
    for expected in (20.0, 40.0, 80.0, 160.0, 160.0):  # capped at 16x
        reg._blacklist["h"] = time.monotonic() - 10 * 160.0
        assert not reg.is_blacklisted("h")  # expired -> readmitted
        assert reg.record_failure("h")      # repeat failure
        assert reg.cooldown_for("h") == expected
    # A straggler exiting 0 while the host is STILL blacklisted must
    # not weaken the doubled cooldown (or clear the streak).
    reg.record_success("h")
    assert reg.cooldown_for("h") == 160.0
    # A recorded success after readmission resets the doubling.
    reg._blacklist.pop("h")
    reg.record_success("h")
    assert reg.cooldown_for("h") == 10.0


def test_worker_state_registry_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_HOST_FAILURE_THRESHOLD", "3")
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN", "42.5")
    reg = WorkerStateRegistry.from_env()
    assert reg._threshold == 3
    assert reg._cooldown == 42.5
    # Explicit arguments win over the env.
    reg = WorkerStateRegistry.from_env(failure_threshold=1,
                                       cooldown_secs=0.0)
    assert reg._threshold == 1 and reg._cooldown == 0.0
    # Malformed env degrades to the defaults, not a crash.
    monkeypatch.setenv("HOROVOD_HOST_FAILURE_THRESHOLD", "lots")
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN", "soon")
    reg = WorkerStateRegistry.from_env()
    assert reg._threshold == 1 and reg._cooldown == 0.0


def test_discovery_script_timeout_is_transient(tmp_path, monkeypatch):
    from horovod_tpu.elastic.discovery import DiscoveryFailure
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\nsleep 30\n")
    script.chmod(0o755)
    # Constructor argument.
    disc = HostDiscoveryScript(str(script), timeout=0.2)
    with pytest.raises(DiscoveryFailure):
        disc.find_available_hosts_and_slots()
    # Env wiring (HOROVOD_DISCOVERY_SCRIPT_TIMEOUT) when no argument.
    monkeypatch.setenv("HOROVOD_DISCOVERY_SCRIPT_TIMEOUT", "0.2")
    disc = HostDiscoveryScript(str(script))
    with pytest.raises(DiscoveryFailure):
        disc.find_available_hosts_and_slots()


def test_discovery_script_nonzero_rc_is_transient(tmp_path):
    from horovod_tpu.elastic.discovery import DiscoveryFailure
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\nexit 7\n")
    script.chmod(0o755)
    with pytest.raises(DiscoveryFailure):
        HostDiscoveryScript(str(script)).find_available_hosts_and_slots()


def test_discovery_script_malformed_slots_skipped(tmp_path):
    # One bad line must not kill the whole pass (it used to raise
    # ValueError and lose the tick): skip it, keep the good hosts.
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\necho host1:4\necho host2:abc\n"
                      "echo host3\n")
    script.chmod(0o755)
    disc = HostDiscoveryScript(str(script), default_slots=2)
    assert disc.find_available_hosts_and_slots() == {
        "host1": 4, "host3": 2}


class _FlakyDiscovery(FixedHosts):
    """FixedHosts that raises DiscoveryFailure while ``failing``."""

    def __init__(self, hosts):
        super().__init__(hosts)
        self.failing = False

    def find_available_hosts_and_slots(self):
        from horovod_tpu.elastic.discovery import DiscoveryFailure
        if self.failing:
            raise DiscoveryFailure("flaking")
        return super().find_available_hosts_and_slots()


def _make_driver(discovery, **kwargs):
    from horovod_tpu.elastic.driver import ElasticDriver
    return ElasticDriver(["true"], discovery, min_np=1, max_np=None,
                         **kwargs)


def _close_driver(driver):
    # The constructor binds both server sockets without starting their
    # serve loops; close the sockets directly (stop() would block on a
    # shutdown handshake the never-started loop cannot answer).
    driver._server._server.server_close()
    driver._kv._httpd.server_close()


def test_discovery_failure_streak_tolerance_and_escalation():
    disc = _FlakyDiscovery({"a": 1})
    driver = _make_driver(disc, discovery_failure_threshold=3)
    reasons = []
    driver._recompute_world = reasons.append
    try:
        driver._discovery_tick()
        assert driver._hosts.current_hosts == {"a": 1}
        assert reasons == ["discovery update"]
        # Failures below the threshold keep the last good view.
        disc.failing = True
        driver._discovery_tick()
        driver._discovery_tick()
        assert driver._hosts.current_hosts == {"a": 1}
        assert reasons == ["discovery update"]
        # The threshold-th consecutive failure escalates: the view is
        # invalidated and the world recomputes onto the below-min_np
        # fail-fast deadline.
        driver._discovery_tick()
        assert driver._hosts.current_hosts == {}
        assert reasons == ["discovery update", "discovery escalation"]
        # Recovery after escalation re-forms the world.
        disc.failing = False
        driver._discovery_tick()
        assert driver._hosts.current_hosts == {"a": 1}
        assert driver._discovery_failures == 0
        assert reasons[-1] == "discovery update"
    finally:
        _close_driver(driver)


def test_discovery_success_resets_failure_streak():
    disc = _FlakyDiscovery({"a": 1})
    driver = _make_driver(disc, discovery_failure_threshold=3)
    driver._recompute_world = lambda reason: None
    try:
        driver._discovery_tick()
        disc.failing = True
        driver._discovery_tick()
        driver._discovery_tick()
        disc.failing = False
        driver._discovery_tick()  # streak broken
        assert driver._discovery_failures == 0
        disc.failing = True
        driver._discovery_tick()
        driver._discovery_tick()
        # 2 < 3: the earlier near-miss streak must not carry over.
        assert driver._hosts.current_hosts == {"a": 1}
    finally:
        _close_driver(driver)


def test_respawn_backoff_grows_and_caps():
    driver = _make_driver(FixedHosts({"127.0.0.1": 1}),
                          respawn_backoff_base=0.02,
                          respawn_backoff_cap=0.08)
    driver._make_worker_proc = lambda slot, env: None  # carrier declines
    slot = ("127.0.0.1", 0)
    try:
        driver._target = [slot]
        backoffs = []
        for _ in range(4):
            time.sleep(0.1)  # > cap: every call is an eligible attempt
            driver._check_procs()
            backoffs.append(driver._spawn_backoff[slot])
        assert backoffs == [0.04, 0.08, 0.08, 0.08]
    finally:
        _close_driver(driver)


def test_elastic_sampler_shard_and_resume():
    s = ElasticSampler(dataset_size=10, shuffle=False)
    # Uninitialized world -> single rank sees everything.
    assert sorted(s) == list(range(10))
    s.record_indices([0, 1, 2, 3])
    s.on_reset()
    assert sorted(s) == [4, 5, 6, 7, 8, 9]
    sd = s.state_dict()
    s2 = ElasticSampler(dataset_size=10, shuffle=False)
    s2.load_state_dict(sd)
    assert sorted(s2) == [4, 5, 6, 7, 8, 9]
    s2.set_epoch(1)
    assert len(s2) == 10


def test_object_state_commit_restore():
    st = ObjectState(batch=0, lr=0.1)
    st.batch = 5
    st.commit()
    st.batch = 9
    st.lr = 0.5
    st.restore()
    assert st.batch == 5 and st.lr == 0.1


def test_commit_id_monotonic_and_restore_preserves_it():
    st = ObjectState(batch=0)
    assert st._commit_id == 0  # construction is not a commit
    st.commit()
    st.commit()
    assert st._commit_id == 2
    st.batch = 99
    st.restore()
    # restore rolls the DATA back to commit 2; the id stays (the
    # restored state IS commit 2, not a new one).
    assert st._commit_id == 2 and st.batch == 0


# -- durable spills (ISSUE 5 tentpole layer 3) -----------------------------

def test_spill_roundtrip_keep_k_and_corrupt_fallback(tmp_path, monkeypatch):
    from horovod_tpu.elastic import spill
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STATE_KEEP", "3")
    for cid in range(1, 6):
        spill.write(cid, b"payload-%d" % cid, "r0")
    names = sorted(os.listdir(str(tmp_path)))
    assert len(names) == 3, names  # keep-last-K pruned commits 1 and 2
    assert not [n for n in names if n.startswith(".tmp")]
    assert spill.load_newest() == (5, b"payload-5")
    # Torn tail on the newest: restore falls back to the previous blob.
    newest = [p for c, p in spill.scan() if c == 5][0]
    blob = open(newest, "rb").read()
    with open(newest, "wb") as f:
        f.write(blob[:-3])
    assert spill.load_newest() == (4, b"payload-4")
    # Bit flip inside the payload: the CRC catches it.
    p4 = [p for c, p in spill.scan() if c == 4][0]
    raw = bytearray(open(p4, "rb").read())
    raw[-1] ^= 0xFF
    with open(p4, "wb") as f:
        f.write(bytes(raw))
    assert spill.load_newest() == (3, b"payload-3")
    # Nothing strictly newer than memory -> no adoption.
    assert spill.load_newest(min_commit_id=3) is None
    assert spill.have_evidence()


def test_spill_fault_injection_torn_write(tmp_path, monkeypatch):
    """elastic.state.spill drop = the write lands truncated mid-payload
    (a host losing power mid-commit); restore must detect and skip it."""
    from horovod_tpu.common import faultline
    from horovod_tpu.elastic import spill
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    spill.write(1, b"A" * 64, "r0")
    monkeypatch.setenv("HVD_TPU_FAULT", "elastic.state.spill:drop@times=1")
    faultline.reset()
    try:
        spill.write(2, b"B" * 64, "r0")
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
    assert len(spill.scan()) == 2  # the torn file exists on disk ...
    assert spill.load_newest() == (1, b"A" * 64)  # ... and is skipped


def test_spill_prune_sweeps_stale_tmp_files(tmp_path, monkeypatch):
    # A crash between mkstemp and os.replace leaves a temp file; the
    # pruner sweeps it once it is safely past any live write's
    # lifetime, and never touches a fresh (possibly in-flight) one.
    from horovod_tpu.elastic import spill
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    stale = tmp_path / ".tmp-spill-dead"
    stale.write_bytes(b"x")
    old = time.time() - 600
    os.utime(str(stale), (old, old))
    fresh = tmp_path / ".tmp-spill-live"
    fresh.write_bytes(b"y")
    spill.write(1, b"payload", "r0")
    assert not stale.exists()
    assert fresh.exists()


def test_replica_buddies_prefer_other_hosts(monkeypatch):
    # A replica on the source's own host dies with it; host-distinct
    # slots must be picked first.
    from horovod_tpu.elastic import driver as driver_mod
    sent = []
    monkeypatch.setattr(
        driver_mod, "send_message",
        lambda addr, secret, payload, **kw: sent.append(addr))
    d = _make_driver(FixedHosts({"a": 2, "b": 1}))
    try:
        d._target = [("a", 0), ("a", 1), ("b", 0)]
        for slot, addr in [(("a", 0), ("a", 1)), (("a", 1), ("a", 2)),
                           (("b", 0), ("b", 3))]:
            d._worker_addrs.register(slot, addr)
        resp = d._handle({"kind": "replicate", "host": "a", "slot": 0,
                          "commit_id": 5, "replicas": 1, "blob": b"x"})
        assert resp["delivered"] == 1
        assert sent == [("b", 3)]  # not the same-host slot ("a", 2)
    finally:
        _close_driver(d)


def test_sync_restores_from_spill_uninitialized_world(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    st = ObjectState(batch=0, total=0.0)
    st.batch, st.total = 4, 8.0
    st.commit()
    # A fresh incarnation (full-job restart) adopts the newest blob.
    st2 = ObjectState(batch=0, total=0.0)
    st2.sync()
    assert st2.batch == 4 and st2.total == 8.0
    assert st2._commit_id == 1


def test_sync_no_valid_blob_fails_loudly(tmp_path, monkeypatch):
    from horovod_tpu.elastic.state import StateSyncError
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    corrupt = tmp_path / "state-00000000000000000003-r0.spill"
    corrupt.write_bytes(b"garbage that is definitely not a spill blob")
    st = ObjectState(batch=0)
    with pytest.raises(StateSyncError):
        st.sync()
    # An EMPTY spill dir is a genuine fresh start, never an error.
    corrupt.unlink()
    st.sync()
    assert st.batch == 0


def test_jax_state_spill_roundtrip(tmp_path, monkeypatch):
    from horovod_tpu.elastic.state import JaxState
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    st = JaxState(params=params, epoch=0)
    st.params = {"w": st.params["w"] + 2.5}
    st.epoch = 3
    st.commit()
    st2 = JaxState(params={"w": np.zeros((2, 3), np.float32)}, epoch=0)
    st2.sync()
    assert st2.epoch == 3 and st2._commit_id == 1
    np.testing.assert_array_equal(
        np.asarray(st2.params["w"]),
        np.arange(6, dtype=np.float32).reshape(2, 3) + 2.5)


# -- survivor-elected state root (ISSUE 5 tentpole layer 2) ----------------

def test_elect_state_root_prefers_progress_then_low_rank(monkeypatch):
    from horovod_tpu.jax import functions
    recs = [{"rank": 0, "commit_id": 0, "evidence": False},
            {"rank": 1, "commit_id": 7, "evidence": False},
            {"rank": 2, "commit_id": 7, "evidence": False}]
    monkeypatch.setattr(functions, "allgather_object",
                        lambda obj, name=None: recs)
    root, records = functions.elect_state_root(recs[0])
    assert root["rank"] == 1  # max progress, ties to the LOWEST rank
    assert records is recs
    # All blank (fresh world): degenerates to the reference's rank 0.
    recs0 = [{"rank": r, "commit_id": 0} for r in (2, 0, 1)]
    monkeypatch.setattr(functions, "allgather_object",
                        lambda obj, name=None: recs0)
    root, _ = functions.elect_state_root(recs0[0])
    assert root["rank"] == 0


# -- drain protocol bookkeeping (ISSUE 5 tentpole layer 1) -----------------

class _FakeProc:
    def __init__(self, rc):
        self._rc = rc
        self.terminated = False

    def poll(self):
        return self._rc

    def terminate(self):
        self.terminated = True


def test_drained_worker_is_planned_removal_not_failure():
    """Satellite: a drained (or clean-exit-0) worker resets the slot's
    respawn backoff and never contributes to
    HOROVOD_HOST_FAILURE_THRESHOLD — no blacklist, no respawn churn."""
    from horovod_tpu.elastic.worker import DRAIN_EXIT_CODE
    driver = _make_driver(FixedHosts({"h": 1}), failure_threshold=1)
    driver._make_worker_proc = lambda slot, env: None
    slot = ("h", 0)
    recomputes = []
    driver._recompute_world = recomputes.append
    try:
        driver._target = [slot]
        driver._published = True
        # (1) rc fallback: the drain notice was lost, the distinguished
        # exit code alone marks the removal as planned.
        driver._spawn_backoff[slot] = 8.0
        driver._procs[slot] = _FakeProc(DRAIN_EXIT_CODE)
        driver._spawn_attempts[slot] = time.monotonic()
        assert driver._check_procs() is False
        assert driver._registry.blacklisted_hosts() == []
        assert driver._registry._failures == {}
        assert slot not in driver._spawn_backoff  # backoff reset
        assert slot not in driver._succeeded      # but not "done" either
        assert recomputes == ["worker drained"]
        # (2) notice path: after a drain message ANY rc is planned
        # (SIGKILL beat the clean exit).
        resp = driver._handle({"kind": "drain", "host": "h", "slot": 0,
                               "commit_id": 3, "reason": "preemption"})
        assert resp.get("ok"), resp
        driver._procs[slot] = _FakeProc(137)
        driver._spawn_attempts[slot] = time.monotonic()
        assert driver._check_procs() is False
        assert driver._registry.blacklisted_hosts() == []
        assert driver._registry._failures == {}
        assert recomputes == ["worker drained", "worker drained"]
        assert slot not in driver._draining  # consumed by the reap
        # (3) clean exit 0 resets the backoff too and counts as done.
        driver._spawn_backoff[slot] = 8.0
        driver._procs[slot] = _FakeProc(0)
        driver._spawn_attempts[slot] = time.monotonic()
        assert driver._check_procs() is True  # all target slots done
        assert slot not in driver._spawn_backoff
        # (4) an actual failure still counts toward the threshold.
        driver._succeeded.discard(slot)
        driver._procs[slot] = _FakeProc(17)
        driver._spawn_attempts[slot] = time.monotonic()
        driver._check_procs()
        assert driver._registry.blacklisted_hosts() == ["h"]
    finally:
        _close_driver(driver)


def test_drained_slot_not_respawned_in_same_reap_pass():
    """Regression (found live by the straggler-drain e2e): the reap
    pass that books a drain runs its spawn list BEFORE the epoch-bump
    recompute, so a same-pass respawn of the drained slot could
    rendezvous into the still-PUBLISHED stale epoch, resolve the OLD
    world's jax coordinator, and FATAL the survivors mid-recovery
    (new-incarnation connect propagated by error polling).  The
    drained slot must sit out its own reap pass — the failure path
    already does, via failed_hosts — and respawn only after the world
    recompute, where the fresh worker parks on "wait" until the new
    epoch publishes."""
    from horovod_tpu.elastic.worker import DRAIN_EXIT_CODE
    driver = _make_driver(FixedHosts({"h": 1}))
    slot = ("h", 0)
    spawned = []
    driver._spawn_workers = lambda slots: spawned.extend(slots)
    recomputes = []
    driver._recompute_world = recomputes.append
    try:
        driver._target = [slot]
        driver._published = True
        driver._procs[slot] = _FakeProc(DRAIN_EXIT_CODE)
        # No spawn-attempt stamp: without the drained-slot exclusion
        # the throttle alone would happily respawn in this very pass.
        assert driver._check_procs() is False
        assert spawned == []                      # sat out its pass
        assert recomputes == ["worker drained"]   # epoch bump booked
        # The NEXT pass (post-recompute world) respawns it normally.
        assert driver._check_procs() is False
        assert spawned == [slot]
    finally:
        _close_driver(driver)


def test_drain_ack_drop_falls_back_to_exit_code(monkeypatch):
    """driver.drain.ack drop: the notice is lost at the driver; the
    slot is NOT marked draining, but the drain exit code still lands
    the worker in the planned-removal path."""
    from horovod_tpu.common import faultline
    from horovod_tpu.elastic.worker import DRAIN_EXIT_CODE
    monkeypatch.setenv("HVD_TPU_FAULT", "driver.drain.ack:drop")
    faultline.reset()
    driver = _make_driver(FixedHosts({"h": 1}))
    driver._make_worker_proc = lambda slot, env: None
    driver._recompute_world = lambda reason: None
    slot = ("h", 0)
    try:
        driver._target = [slot]
        resp = driver._handle({"kind": "drain", "host": "h", "slot": 0,
                               "commit_id": 3, "reason": "preemption"})
        assert "error" in resp
        assert slot not in driver._draining
        driver._procs[slot] = _FakeProc(DRAIN_EXIT_CODE)
        driver._spawn_attempts[slot] = time.monotonic()
        driver._check_procs()
        assert driver._registry.blacklisted_hosts() == []
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
        _close_driver(driver)


def test_stall_error_aborts_via_drain_path(monkeypatch):
    """Satellite: a StallError (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS
    crossed) leaves through the drain protocol — committed-then-abort
    with the distinguished exit code — not a hard crash that would
    blacklist the healthy host that merely watched a peer die."""
    import horovod_tpu.elastic.worker as worker_mod
    from horovod_tpu.elastic import state as state_mod
    from horovod_tpu.elastic.worker import WorkerDrained
    from horovod_tpu.ops.engine import HorovodInternalError
    from horovod_tpu.utils.stall_inspector import StallError
    monkeypatch.setattr(worker_mod, "_manager", None)  # fresh singleton
    monkeypatch.setenv("HOROVOD_PREEMPT_GRACE_SECS", "0")  # no timer
    st = ObjectState(batch=2)
    st.commit()
    st.batch = 9  # half-applied step the abort must roll back
    # The engine wraps handle errors in HorovodInternalError with the
    # original as __cause__ (CollectiveHandle.wait raises `from`).
    cause = StallError("tensor 'b3' stalled beyond the threshold")
    exc = HorovodInternalError(str(cause))
    exc.__cause__ = cause
    with pytest.raises(WorkerDrained) as ei:
        state_mod._stall_abort(st, exc)
    assert ei.value.code == worker_mod.DRAIN_EXIT_CODE
    assert worker_mod.notification_manager().drain_requested()
    assert st.batch == 2  # restored to the last commit before aborting


def test_stall_abort_detection_covers_both_planes():
    # In-process engine: StallError chained as __cause__.  Native
    # core: Aborted status text only (operations.cc).  Anything else
    # stays on the restore-and-rejoin path.
    from horovod_tpu.elastic.state import _is_stall_abort
    from horovod_tpu.ops.engine import HorovodInternalError
    from horovod_tpu.utils.stall_inspector import StallError
    chained = HorovodInternalError("collective 'b3' failed")
    chained.__cause__ = StallError("stalled")
    assert _is_stall_abort(chained)
    assert _is_stall_abort(
        HorovodInternalError("stall shutdown threshold exceeded"))
    assert not _is_stall_abort(HorovodInternalError("peer closed"))


class _FakeMetadata:
    """GCE-style metadata server: worker-network-endpoints +
    unhealthy-workers, both mutable by the test."""

    def __init__(self):
        import http.server

        self.values = {"worker-network-endpoints": "",
                       "unhealthy-workers": None}
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                key = self.path.rsplit("/", 1)[-1]
                val = outer.values.get(key)
                if (val is None
                        or not self.path.startswith(
                            "/computeMetadata/v1/instance/attributes/")):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = val.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # noqa: D102 - silence
                pass

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return "http://127.0.0.1:%d/computeMetadata/v1" % \
            self.server.server_address[1]

    def stop(self):
        self.server.shutdown()


def test_tpu_slice_discovery_parsing():
    from horovod_tpu.elastic.discovery import TpuSliceDiscovery
    md = _FakeMetadata()
    try:
        # TPU VM triple form, host:port form, bare-host form.
        md.values["worker-network-endpoints"] = (
            "t1v-n-x-w-0:8470:10.0.0.1, 10.0.0.2:8470,10.0.0.3")
        disc = TpuSliceDiscovery(base_url=md.url, slots_per_host=4)
        assert disc.find_available_hosts_and_slots() == {
            "10.0.0.1": 4, "10.0.0.2": 4, "10.0.0.3": 4}
        # A preemption notice removes the host before it dies; the
        # missing unhealthy-workers attribute (404) means none.
        md.values["unhealthy-workers"] = "10.0.0.2"
        assert disc.find_available_hosts_and_slots() == {
            "10.0.0.1": 4, "10.0.0.3": 4}
    finally:
        md.stop()


# -- integration: real local worker processes ------------------------------

def _env():
    env = dict(os.environ)
    # REPLACE PYTHONPATH, never prepend: this box's ambient entry
    # (.axon_site) carries a sitecustomize that PRE-INITIALIZES the
    # JAX runtime in every child, which breaks the multihost workers'
    # jax.distributed join (they would each form a 1-process world).
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_ELASTIC_DRIVER_ADDR", None)
    return env


WORKER_COMMON = """
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0, total=0.0)
"""


def test_elastic_fixed_world_completes(tmp_path):
    """Static elastic run: 2 workers, commits every batch, clean finish."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while state.batch < 5:
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.total += float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d total=%.1f"
          % (hvd.rank(), hvd.size(), state.total), flush=True)
    return state.total

train(state)
""")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--min-np", "2", "--max-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(240), env=_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DONE rank=0 size=2 total=10.0" in proc.stdout
    assert "DONE rank=1 size=2 total=10.0" in proc.stdout


def test_elastic_worker_failure_blacklist_and_resume(tmp_path):
    """A worker dies mid-training: its host is blacklisted, the survivor
    restores the last commit and finishes alone (reference fault
    injection: kill a real worker process)."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while state.batch < 8:
        if (os.environ.get("HOROVOD_HOSTNAME") == "127.0.0.2"
                and state.batch == 3):
            os._exit(17)  # simulated hardware failure
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.total += float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(240), env=_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Survivor finished the epoch alone after the resize.
    assert "DONE rank=0 size=1 batch=8" in proc.stdout


def test_elastic_scale_up_mid_run(tmp_path):
    """Discovery output gains a host mid-run: workers re-rendezvous into
    the larger world and the joiner syncs state (reference: discovery
    script output mutated mid-test)."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("127.0.0.1:2\n")
    disc = tmp_path / "disc.sh"
    disc.write_text("#!/bin/sh\ncat %s\n" % hosts_file)
    disc.chmod(0o755)
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
state.extra = 0

@elastic.run
def train(state):
    while hvd.size() < 3 or state.extra < 3:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        if hvd.size() >= 3:
            state.extra += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%d size=%d" % (hvd.rank(), hvd.size()), flush=True)

train(state)
""")

    def add_host_later():
        time.sleep(12.0)
        hosts_file.write_text("127.0.0.1:2\n127.0.0.2:1\n")

    t = threading.Thread(target=add_host_later, daemon=True)
    t.start()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", str(disc),
         "--min-np", "2", "--max-np", "4",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300), env=_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert "DONE rank=%d size=3" % r in proc.stdout, proc.stdout


def test_elastic_multihost_resize(tmp_path):
    """Elastic scale-up of a MULTIHOST (device-payload) world: on the
    epoch change every worker leaves the global JAX runtime
    (jax.distributed shutdown), re-rendezvouses, and rejoins the
    resized runtime; device collectives flow in both worlds (closes
    the r2 gap: elastic was only exercised on the tcp plane)."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("127.0.0.1:1\n127.0.0.2:1\n")
    disc = tmp_path / "disc.sh"
    disc.write_text("#!/bin/sh\ncat %s\n" % hosts_file)
    disc.chmod(0o755)
    started = tmp_path / "started"
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
state.extra = 0

@elastic.run
def train(state):
    while hvd.size() < 3 or state.extra < 2:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        assert float(np.asarray(out)[0]) == float(hvd.size())
        state.batch += 1
        if state.batch == 3 and hvd.rank() == 0:
            open("@STARTED@", "w").close()  # initial world is training
        if hvd.size() >= 3:
            state.extra += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%d size=%d" % (hvd.rank(), hvd.size()), flush=True)

train(state)
""".replace("@STARTED@", str(started)))

    def add_host_when_started():
        # Progress-triggered (not a fixed delay): under full-suite load
        # on one core the initial world can take >15s to even start.
        deadline = time.time() + 240
        while not started.exists() and time.time() < deadline:
            time.sleep(0.5)
        time.sleep(1.0)
        hosts_file.write_text(
            "127.0.0.1:1\n127.0.0.2:1\n127.0.0.3:1\n")

    t = threading.Thread(target=add_host_when_started, daemon=True)
    t.start()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--multihost",
         "--host-discovery-script", str(disc),
         "--min-np", "2", "--max-np", "3",
         sys.executable, str(script)],
        # 1-core box: under full-suite load the three jax runtimes
        # start several times slower than when run alone (observed one
        # >600s flake in a 27-minute suite run)
        capture_output=True, text=True, timeout=scaled_timeout(900), env=_env(),
        cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert "DONE rank=%d size=3" % r in proc.stdout, proc.stdout


def test_elastic_multihost_watchdog_recovery(tmp_path):
    """Elastic x multihost x execution watchdog, integrated (VERDICT r4
    Next #8): a member wedges MID-BURST with the pipeline window full —
    it negotiates the burst's groups but never dispatches its side of
    the compiled programs (the undetectable-on-ICI failure), stays
    alive past the watchdog window, then dies.  The survivor must
    (1) fail the in-flight handles loudly via the device-exec watchdog,
    (2) let the elastic machinery blacklist the dead host and resize,
    (3) resume from the last commit on the new world and finish."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
BURST = 4

@elastic.run
def train(state):
    while state.batch < 6:
        doomed = (hvd.size() > 1 and state.batch == 2
                  and os.environ.get("HOROVOD_HOSTNAME") == "127.0.0.2")
        if doomed:
            # Negotiate the burst (control plane sees this rank ready)
            # but never dispatch the device programs; stay alive so
            # the transport looks healthy, then die.
            from horovod_tpu.common import basics
            eng = basics._get_mh_engine()
            eng._execute = lambda g: None
            for i in range(BURST):
                hvd.allreduce_async(np.ones(4, np.float32), op=hvd.Sum,
                                    name="b%d.%d" % (state.batch, i))
            time.sleep(40)
            os._exit(17)
        hs = [hvd.allreduce_async(np.ones(4, np.float32), op=hvd.Sum,
                                  name="b%d.%d" % (state.batch, i))
              for i in range(BURST)]
        try:
            vals = [float(np.asarray(h.wait(120)).reshape(-1)[0])
                    for h in hs]
        except Exception as exc:
            if "watchdog" in str(exc):
                print("WATCHDOG_SEEN rank=%d batch=%d"
                      % (hvd.rank(), state.batch), flush=True)
            raise
        assert vals[0] == float(hvd.size()), vals
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--multihost",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(600),
        env=dict(_env(), **{
            "HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS": "8",
            "HOROVOD_MAX_INFLIGHT_GROUPS": "4",
        }), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # The survivor saw the watchdog diagnostic (not a transport error:
    # the wedged member was alive when the timeout fired) ...
    assert "WATCHDOG_SEEN rank=0 batch=2" in proc.stdout, proc.stdout
    # ... and resumed from the commit on the shrunken world.
    assert "DONE rank=0 size=1 batch=6" in proc.stdout, proc.stdout


@pytest.mark.slow
def test_elastic_multihost_deadline_expiry_restores_from_commit(tmp_path):
    """ISSUE 18 acceptance: elastic x multihost x per-collective
    deadline, integrated.  At batch 2 every worker arms
    ``mh.deadline.wedge`` (once per process): the next negotiated group
    is registered and deadline-stamped but its dispatch is withheld —
    a program that never starts.  The 8 s deadline must expire it, the
    engine poisons with the RESTORE-shaped CollectiveDeadlineExceeded
    (never the drain-shaped stall text), and the elastic loop restores
    every worker from the last commit IN-PROCESS: the world stays size
    2, training resumes at batch 2, and the final total proves zero
    committed steps were lost or double-counted."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
ARMED = {"done": False}

@elastic.run
def train(state):
    while state.batch < 6:
        if state.batch == 2 and not ARMED["done"]:
            # Same SPMD point on every rank; the process-global flag
            # keeps the post-restore replay of batch 2 from re-arming.
            ARMED["done"] = True
            os.environ["HVD_TPU_FAULT"] = "mh.deadline.wedge:drop@times=1"
            from horovod_tpu.common import faultline
            faultline.reset()
        try:
            out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                                name="b%d" % state.batch)
        except Exception as exc:
            assert "stall shutdown threshold" not in str(exc), exc
            if "deadline" in str(exc):
                print("DEADLINE_SEEN rank=%d batch=%d"
                      % (hvd.rank(), state.batch), flush=True)
            raise
        state.total += float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d total=%.1f"
          % (hvd.rank(), hvd.size(), state.batch, state.total),
          flush=True)

train(state)
""")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--multihost",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(600),
        env=dict(_env(), **{
            "HOROVOD_COLLECTIVE_TIMEOUT_SECS": "8",
        }), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Both workers hit the expiry (the wedge armed on every rank) ...
    assert "DEADLINE_SEEN rank=0 batch=2" in proc.stdout, proc.stdout
    assert "DEADLINE_SEEN rank=1 batch=2" in proc.stdout, proc.stdout
    # ... and BOTH survived the restore: same processes, full-size
    # world, resumed from the batch-2 commit with an exact total
    # (2.0 per batch x 6 batches — nothing lost, nothing replayed).
    assert "DONE rank=0 size=2 batch=6 total=12.0" in proc.stdout, \
        proc.stdout
    assert "DONE rank=1 size=2 batch=6 total=12.0" in proc.stdout, \
        proc.stdout
    # The drain-shaped abort never fired anywhere in the world.
    assert "stall shutdown threshold" not in proc.stdout + proc.stderr


def test_tpu_discovery_preemption_resizes_world(tmp_path):
    """A preemption notice appears on the fake TPU metadata server
    mid-run: the driver drops the host from the slice view, the doomed
    worker is stopped, and the survivor re-rendezvouses into a smaller
    world and finishes from committed state (SURVEY §5: control-plane
    preemption notices play the discovery-script role)."""
    md = _FakeMetadata()
    md.values["worker-network-endpoints"] = (
        "w0:8470:127.0.0.1,w1:8470:127.0.0.2")
    started = tmp_path / "started"
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
state.extra = 0

@elastic.run
def train(state):
    while hvd.size() > 1 or state.extra < 3:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        if state.batch == 3 and hvd.rank() == 0:
            open("@STARTED@", "w").close()  # 2-rank world is training
        if hvd.size() == 1:
            state.extra += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""".replace("@STARTED@", str(started)))

    def preempt_when_started():
        # Progress-triggered, not a fixed delay (see the resize test).
        deadline = time.time() + 240
        while not started.exists() and time.time() < deadline:
            time.sleep(0.5)
        time.sleep(1.0)
        md.values["unhealthy-workers"] = "127.0.0.2"

    t = threading.Thread(target=preempt_when_started, daemon=True)
    t.start()
    env = _env()
    env["HVD_TPU_METADATA_URL"] = md.url
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner",
             "--tpu-discovery", "--min-np", "1", "--max-np", "2",
             sys.executable, str(script)],
            capture_output=True, text=True, timeout=scaled_timeout(600), env=env,
            cwd=REPO)
    finally:
        md.stop()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DONE rank=0 size=1" in proc.stdout, proc.stdout


def test_elastic_die_injection_recovery(tmp_path):
    """The worker-kill recovery scenario driven by the fault plane
    instead of a hand-written os._exit: HVD_TPU_FAULT arms a `die` at
    the commit seam, conditioned on the victim host, so EVERY worker
    runs identical user code and the injection env alone picks the
    casualty.  The driver must reap the rc, blacklist the host, and
    the survivor must restore from commit and finish alone."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while state.batch < 6:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.total += float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""")
    env = _env()
    env["HVD_TPU_FAULT"] = "elastic.state.commit:die:21@host=127.0.0.2"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(240),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DONE rank=0 size=1 batch=6" in proc.stdout, proc.stdout


def test_elastic_blacklist_cooldown_rejoin(tmp_path):
    """Blacklist cooldown, end to end: a die-injected host is
    blacklisted, the survivor resumes alone, the cooldown expires, the
    host re-enters discovery, its worker respawns and rejoins via the
    normal re-rendezvous, and the run finishes with the FULL world.
    The injection fires only in world epoch 1 (@epoch=1), so the
    respawned worker on the same host proves recovery, not death."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
state.extra = 0

@elastic.run
def train(state):
    while hvd.size() < 2 or state.extra < 3:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        if hvd.size() >= 2:
            state.extra += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%d size=%d" % (hvd.rank(), hvd.size()), flush=True)

train(state)
""")
    env = _env()
    env["HVD_TPU_FAULT"] = \
        "elastic.state.commit:die:21@host=127.0.0.2@epoch=1"
    env["HOROVOD_BLACKLIST_COOLDOWN"] = "3"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         "--max-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # The host was blacklisted with a cooldown, expired, and rejoined:
    # BOTH ranks finish in a size-2 world.
    for r in range(2):
        assert "DONE rank=%d size=2" % r in proc.stdout, \
            proc.stdout + proc.stderr
    assert "blacklisting host 127.0.0.2" in proc.stderr, proc.stderr
    assert "cooldown" in proc.stderr, proc.stderr


def test_elastic_discovery_flake_recovery(tmp_path):
    """A bounded discovery-flake window (drop @after=2 @times=2, under
    the default HOROVOD_DISCOVERY_FAILURE_THRESHOLD=3) is absorbed on
    the last good host view: the world never changes and the run
    completes cleanly."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while state.batch < 40:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""")
    env = _env()
    env["HVD_TPU_FAULT"] = "elastic.discovery.run:drop@after=2@times=2"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert "DONE rank=%d size=2 batch=40" % r in proc.stdout, \
            proc.stdout + proc.stderr
    assert "keeping last good host view" in proc.stderr, proc.stderr


def test_elastic_discovery_escalation_fails_fast(tmp_path):
    """The escalation boundary: discovery fails PERSISTENTLY (drop with
    no @times bound), the failure streak crosses the threshold, the
    driver discards the host view, and the run dies LOUDLY via the
    elastic below-min_np deadline — no hang, no indefinite training on
    a stale world view."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while True:
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                      name="b%d" % state.batch)
        state.batch += 1
        time.sleep(0.05)
        state.commit()

train(state)
""")
    env = _env()
    env["HVD_TPU_FAULT"] = "elastic.discovery.run:drop@after=4"
    env["HOROVOD_ELASTIC_EXIT_GRACE"] = "5"
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         "--elastic-timeout", "6",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(240),
        env=env, cwd=REPO)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "escalating" in proc.stderr, proc.stderr
    assert "below min_np" in proc.stderr, proc.stderr
    assert time.monotonic() - t0 < scaled_timeout(180)


def test_elastic_spawn_drop_respawn_backoff_recovers(tmp_path):
    """driver.spawn.attempt drop: both initial spawn attempts are
    declined by injection; the reap loop's exponential respawn backoff
    retries them and the world still forms and finishes."""
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
@elastic.run
def train(state):
    while state.batch < 3:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d" % (hvd.rank(), hvd.size()), flush=True)

train(state)
""")
    env = _env()
    env["HVD_TPU_FAULT"] = "driver.spawn.attempt:drop@times=2"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert "DONE rank=%d size=2" % r in proc.stdout, \
            proc.stdout + proc.stderr
    assert "dropped (faultline driver.spawn.attempt)" in proc.stderr, \
        proc.stderr


DRAIN_WORKER = """
import hashlib, os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0, params=np.zeros(8, np.float32))

@elastic.run
def train(state):
    print("SYNCED rank=%d batch=%d commit=%d root=%s"
          % (hvd.rank(), state.batch, state._commit_id,
             state._sync_root), flush=True)
    while state.batch < 8:
        out = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.params = state.params + np.asarray(out)
        state.batch += 1
        state.commit()
    digest = hashlib.md5(np.asarray(state.params,
                                    np.float32).tobytes()).hexdigest()
    print("DONE rank=%d size=%d batch=%d params=%s"
          % (hvd.rank(), hvd.size(), state.batch, digest), flush=True)

train(state)
"""


def test_elastic_preemption_drain_survivor_elected_root(tmp_path):
    """ISSUE 5 acceptance: injected preemption (worker.preempt.sigterm)
    on the rank-0 host mid-epoch → the worker finishes the in-flight
    step, commits, sends an acked drain notice, and exits with the
    drain code; the driver treats it as a PLANNED removal (no
    blacklist, no failure count); the respawned blank worker must NOT
    win the root election — the survivor (max commit id) does, and the
    restored params are bitwise-identical on all ranks."""
    script = tmp_path / "train.py"
    script.write_text(DRAIN_WORKER)
    env = _env()
    # Fires on the 3rd commit of the epoch-1 worker on 127.0.0.1 (the
    # rank-0 host): mid-epoch, after real progress exists.  The
    # respawned worker runs in epoch >= 2, so the injection never
    # re-fires and the world proves recovery.
    env["HVD_TPU_FAULT"] = \
        "worker.preempt.sigterm:drop@host=127.0.0.1@epoch=1@after=2@times=1"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         "--max-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Drain sequence: worker announced it, driver acked and treated
    # the exit as planned ...
    assert "draining at commit 3" in proc.stderr, proc.stderr
    assert "planned removal" in proc.stderr, proc.stderr
    # ... with NO blacklist entry (the whole point: preemption is not
    # a host failure).
    assert "blacklisting host" not in proc.stderr, proc.stderr
    # The respawned blank worker (rank 0 again: first host in target
    # order) adopted the SURVIVOR's progress via the elected root —
    # commit id 3, root rank 1, not a zero-filled restart.
    assert "SYNCED rank=0 batch=3 commit=3 root=1" in proc.stdout, \
        proc.stdout + proc.stderr
    # Both ranks finished the epoch with bitwise-identical params.
    digests = {line.split("params=")[1].strip()
               for line in proc.stdout.splitlines()
               if "DONE rank=" in line and "batch=8" in line}
    done = [line for line in proc.stdout.splitlines()
            if "DONE rank=" in line]
    assert len(done) == 2 and len(digests) == 1, \
        proc.stdout + proc.stderr


SPILL_WORKER = """
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0, total=0.0)

@elastic.run
def train(state):
    print("ENTER rank=%d batch=%d commit=%d"
          % (hvd.rank(), state.batch, state._commit_id), flush=True)
    while state.batch < 6:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.total += float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d total=%.1f"
          % (hvd.rank(), hvd.size(), state.batch, state.total),
          flush=True)

train(state)
"""


def test_elastic_full_restart_restores_from_spill(tmp_path):
    """ISSUE 5 acceptance: EVERY worker dies at once (whole-job
    preemption) with durable spills on; a fresh run over the same
    spill dir restores from the newest VALID blob — the newest blob
    itself was torn by injection (elastic.state.spill), so restore
    falls back to the previous commit.  Run 1: commits 1-5 spill (#5
    torn), all workers die at commit 6.  Run 2: resumes at commit 4."""
    spill_dir = tmp_path / "spills"
    script = tmp_path / "train.py"
    script.write_text(SPILL_WORKER)
    env = _env()
    env["HOROVOD_STATE_SPILL_DIR"] = str(spill_dir)
    env1 = dict(env)
    env1["HVD_TPU_FAULT"] = ("elastic.state.spill:drop@after=4@times=1,"
                             "elastic.state.commit:die:21@after=5")
    env1["HOROVOD_ELASTIC_EXIT_GRACE"] = "5"
    proc1 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         "--elastic-timeout", "6",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env1, cwd=REPO)
    # Multi-host loss: the whole run fails (both hosts die at commit 6).
    assert proc1.returncode != 0, proc1.stdout + proc1.stderr
    from horovod_tpu.elastic import spill
    on_disk = spill.scan(str(spill_dir))
    assert on_disk and max(c for c, _ in on_disk) == 5, on_disk
    # Run 2: fresh job, same spill dir, no faults.  Commit 5's blob is
    # torn on disk -> restore falls back to commit 4 and finishes.
    proc2 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "skipping corrupt spill" in proc2.stderr, proc2.stderr
    for r in range(2):
        assert "ENTER rank=%d batch=4 commit=4" % r in proc2.stdout, \
            proc2.stdout + proc2.stderr
        # total: 4 restored batches x 2.0 + 2 fresh batches x 2.0
        assert "DONE rank=%d size=2 batch=6 total=12.0" % r \
            in proc2.stdout, proc2.stdout + proc2.stderr


def test_elastic_unformable_world_worker_deadline(tmp_path):
    """ISSUE 2 acceptance: a permanently-unformable world leaves NO
    worker alive past HOROVOD_ELASTIC_TIMEOUT + eps.  The driver is
    SIGKILLed (no cleanup) and one worker SIGKILLed, so the survivor's
    collective fails and its rejoin faces an unreachable driver
    forever.  Pre-fix the rejoin retry loop reset its clock around a
    hardcoded 600 s deadline (workers observed alive 13x past the
    env); post-fix ONE monotonic deadline spans every retry and a
    last-resort os._exit covers a wedged teardown."""
    import signal

    timeout_s = 6.0
    script = tmp_path / "train.py"
    script.write_text(WORKER_COMMON + """
print("WORKER_PID %d %s" % (
    os.getpid(), os.environ.get("HOROVOD_HOSTNAME", "?")), flush=True)

@elastic.run
def train(state):
    while True:
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                      name="b%d" % state.batch)
        state.batch += 1
        if state.batch == 3:
            print("TRAINING %d" % hvd.rank(), flush=True)
        time.sleep(0.05)
        state.commit()

train(state)
""")
    env = _env()
    env["HOROVOD_ELASTIC_EXIT_GRACE"] = "5"
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         "--elastic-timeout", str(timeout_s),
         sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO, start_new_session=True)

    pids = {}        # host -> worker pid
    training = set()
    lines = []

    def read_output():
        for line in iter(proc.stdout.readline, ""):
            lines.append(line)
            if "WORKER_PID" in line:
                tail = line.split("WORKER_PID", 1)[1].split()
                pids[tail[1]] = int(tail[0])
            if "TRAINING" in line:
                training.add(line.split("TRAINING", 1)[1].split()[0])

    t = threading.Thread(target=read_output, daemon=True)
    t.start()

    def alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    survivor = None
    try:
        deadline = time.monotonic() + scaled_timeout(120)
        while (len(pids) < 2 or len(training) < 2) \
                and time.monotonic() < deadline:
            assert proc.poll() is None, "".join(lines)
            time.sleep(0.2)
        assert len(pids) == 2 and len(training) == 2, "".join(lines)
        survivor, victim = pids["127.0.0.1"], pids["127.0.0.2"]
        # Driver dies uncleanly (no worker teardown), then the peer:
        # the survivor is on its own with an unreachable driver.
        os.kill(proc.pid, signal.SIGKILL)
        os.kill(victim, signal.SIGKILL)
        t0 = time.monotonic()
        budget = scaled_timeout(timeout_s + 5 + 15)  # timeout+grace+eps
        while alive(survivor) and time.monotonic() - t0 < budget:
            time.sleep(0.25)
        gone_after = time.monotonic() - t0
        assert not alive(survivor), (
            "survivor pid %d still alive %.1fs after the world became "
            "unformable (HOROVOD_ELASTIC_TIMEOUT=%s):\n%s"
            % (survivor, gone_after, timeout_s, "".join(lines)))
    finally:
        for pid in list(pids.values()) + [proc.pid]:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        proc.wait(timeout=30)


SHARD_SPILL_WORKER = """
import hashlib, os, sys
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.common import metrics

hvd.init()
rng = np.random.RandomState(7)
state = elastic.JaxState(
    params={"w": rng.randn(64, 8).astype(np.float32),
            "b": rng.randn(64).astype(np.float64)},
    batch=0)


def state_hash(state):
    h = hashlib.sha256()
    for k in sorted(state.params):
        h.update(np.ascontiguousarray(
            np.asarray(state.params[k])).tobytes())
    return h.hexdigest()[:16]


@elastic.run
def train(state):
    print("ENTER rank=%d size=%d batch=%d commit=%d hash=%s"
          % (hvd.rank(), hvd.size(), state.batch, state._commit_id,
             state_hash(state)), flush=True)
    print("RESTORE_BYTES rank=%d bytes=%d"
          % (hvd.rank(),
             int(metrics.series_sum("shardspill_restore_bytes_total"))),
          flush=True)
    while state.batch < 6:
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.params["w"] = state.params["w"] + float(np.asarray(out)[0])
        state.batch += 1
        state.commit()
        print("COMMIT rank=%d commit=%d hash=%s"
              % (hvd.rank(), state._commit_id, state_hash(state)),
              flush=True)
    print("DONE rank=%d size=%d batch=%d hash=%s"
          % (hvd.rank(), hvd.size(), state.batch, state_hash(state)),
          flush=True)


train(state)
"""


@pytest.mark.slow
def test_shard_spill_n_to_m_restore(tmp_path):
    """ISSUE 15 acceptance: a 2-proc world's SHARDED commit restores
    bitwise-identical state into a 1-proc world (2→1) AND a 3-proc
    world (2→3), per-host restore I/O < full-state size in the 3-proc
    world, and a torn shard (elastic.state.shard@shard=1@rank=0 —
    rank 0's buddy copy, the one the reader tries FIRST) falls back
    per shard to the surviving copy without discarding the commit."""
    import shutil

    spill_dir = tmp_path / "spills"
    script = tmp_path / "train.py"
    script.write_text(SHARD_SPILL_WORKER)
    env = _env()
    env["HOROVOD_STATE_SPILL_DIR"] = str(spill_dir)
    env["HOROVOD_STATE_SHARD_SPILL"] = "1"

    # Run 1: 2 writers, commits 1..5 land sharded (rank 0's copy of
    # shard 1 torn every commit), every worker dies at commit 6.
    env1 = dict(env)
    env1["HVD_TPU_FAULT"] = ("elastic.state.shard:drop@shard=1@rank=0,"
                             "elastic.state.commit:die:21@after=5")
    env1["HOROVOD_ELASTIC_EXIT_GRACE"] = "5"
    proc1 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         "--elastic-timeout", "6",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env1, cwd=REPO)
    assert proc1.returncode != 0, proc1.stdout + proc1.stderr
    assert "torn (faultline elastic.state.shard)" in proc1.stderr, \
        proc1.stderr
    import re as _re
    h5 = set(_re.findall(r"COMMIT rank=\d+ commit=5 hash=(\w+)",
                         proc1.stdout))
    assert len(h5) == 1, proc1.stdout  # ranks agree at commit 5
    h5 = h5.pop()
    from horovod_tpu.elastic import shardspill
    manifest = shardspill.load_manifest(5, d=str(spill_dir))
    assert manifest is not None and manifest["n_shards"] == 2
    total = int(manifest["total_bytes"])

    # Freeze the durable state for the second reader world: each run
    # appends its own commits.
    dir_b = tmp_path / "spills_b"
    shutil.copytree(spill_dir, dir_b)

    # Run 2a: 2 -> 1 resharding restore (whole stream, one reader).
    proc2 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1", "--min-np", "1",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env, cwd=REPO)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "ENTER rank=0 size=1 batch=5 commit=5 hash=%s" % h5 \
        in proc2.stdout, proc2.stdout + proc2.stderr
    assert "falling back to the next copy of shard 1" in proc2.stderr, \
        proc2.stderr

    # Run 2b: 2 -> 3 resharding restore (streamed ranges + collective
    # reassembly; per-host restore I/O asserted < full state).
    env_b = dict(env)
    env_b["HOROVOD_STATE_SPILL_DIR"] = str(dir_b)
    proc3 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1,127.0.0.3:1", "--min-np", "3",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(300),
        env=env_b, cwd=REPO)
    assert proc3.returncode == 0, proc3.stdout + proc3.stderr
    for r in range(3):
        assert "ENTER rank=%d size=3 batch=5 commit=5 hash=%s" \
            % (r, h5) in proc3.stdout, proc3.stdout + proc3.stderr
    streamed = {m.group(1): int(m.group(2)) for m in _re.finditer(
        r"RESTORE_BYTES rank=(\d+) bytes=(\d+)", proc3.stdout)}
    assert len(streamed) == 3, proc3.stdout
    # Per-host peak restore I/O strictly under full-state size; the
    # union still covers the whole stream (readers 0/1 own one source
    # shard each, reader 2 owns none in the 2→3 case).
    assert all(v < total for v in streamed.values()), (streamed, total)
    assert sum(streamed.values()) >= total, (streamed, total)


# -- HA control plane: KV failover + driver crash adoption (ISSUE 17) ------

HA_KV_WORKER = """
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.runner.http_client import RendezvousClient

hvd.init()
state = elastic.ObjectState(batch=0)

@elastic.run
def train(state):
    # External HA KV pair via HOROVOD_RENDEZVOUS_ENDPOINTS (no addr,
    # no secret: the out-of-process kv_server runs unauthenticated).
    cli = RendezvousClient()
    while state.batch < 20:
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                      name="b%d" % state.batch)
        state.batch += 1
        state.commit()
        print("STEP rank=%d batch=%d" % (hvd.rank(), state.batch),
              flush=True)
        if state.batch == 10:
            # Park mid-run on the HA KV: the leader is SIGKILLed while
            # every worker polls this key, so finishing at all proves
            # get_blocking re-resolves its endpoint per iteration.
            cli.put("step10/%d" % hvd.rank(), "here")
            cli.get_blocking("go2", timeout=120.0)
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
"""


def _start_kv_server(env, args):
    """Spawn ``python -m horovod_tpu.runner.kv_server`` and parse its
    liveness line; returns (proc, port)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.kv_server"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    line = ""
    for line in iter(proc.stdout.readline, ""):
        if "KV_SERVER LISTENING" in line:
            break
    assert "KV_SERVER LISTENING" in line, line
    port = int(line.split("port=")[1].split()[0])
    # Drain further output so the pipe never fills.
    threading.Thread(target=lambda: [None for _ in
                                     iter(proc.stdout.readline, "")],
                     daemon=True).start()
    return proc, port


def _control_get(port, path):
    import json
    import urllib.request
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=5) as resp:
        return json.loads(resp.read().decode())


@pytest.mark.slow
def test_control_plane_failover_e2e(tmp_path):
    """ISSUE 17 headline: SIGKILL the active KV server while a 2-proc
    elastic run is parked on it mid-training.  The warm standby takes
    over within the lease at a bumped term, every worker fails over
    to it mid-poll, NO training step is lost (each rank runs batches
    1..20 exactly once), no blacklist churn, and the recovered store
    is bitwise-identical to the pre-kill leader snapshot."""
    import signal

    kv_env = _env()
    kv_env.pop("HOROVOD_SECRET_KEY", None)
    kv_env["HOROVOD_CONTROL_LEASE_SECS"] = "1.0"
    leader_proc, lport = _start_kv_server(
        kv_env, ["--host", "127.0.0.1", "--journal-dir",
                 str(tmp_path / "kv-a")])
    standby_proc, sport = _start_kv_server(
        kv_env, ["--host", "127.0.0.1", "--journal-dir",
                 str(tmp_path / "kv-b"),
                 "--standby-of", "127.0.0.1:%d" % lport])

    script = tmp_path / "train.py"
    script.write_text(HA_KV_WORKER)
    env = _env()
    env["HOROVOD_RENDEZVOUS_ENDPOINTS"] = \
        "127.0.0.1:%d,127.0.0.1:%d" % (lport, sport)
    run = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "2",
         sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    try:
        from horovod_tpu.runner.http_client import RendezvousClient
        cli = RendezvousClient(
            endpoints=["127.0.0.1:%d" % lport, "127.0.0.1:%d" % sport])
        # Phase 1 done: both ranks at batch 10, parked on "go2".
        cli.get_blocking("step10/0", timeout=scaled_timeout(180))
        cli.get_blocking("step10/1", timeout=scaled_timeout(180))
        pre_kill = _control_get(lport, "/control/dump")
        # Wait for full replication, then SIGKILL the leader.
        deadline = time.monotonic() + scaled_timeout(30)
        while time.monotonic() < deadline:
            if _control_get(sport, "/control/dump")["kv"] \
                    == pre_kill["kv"]:
                break
            time.sleep(0.1)
        leader_proc.send_signal(signal.SIGKILL)
        leader_proc.wait(timeout=10)
        # Standby promotes within the lease, at a bumped term ...
        deadline = time.monotonic() + scaled_timeout(30)
        status = {}
        while time.monotonic() < deadline:
            status = _control_get(sport, "/control/status")
            if status["role"] == "leader":
                break
            time.sleep(0.1)
        assert status.get("role") == "leader", status
        assert status["term"] >= 2, status
        # ... with the recovered store bitwise-identical to the
        # pre-kill leader snapshot.
        post = _control_get(sport, "/control/dump")
        assert post["kv"] == pre_kill["kv"]
        assert post["seq"] >= pre_kill["seq"]
        # Release phase 2 through the NEW leader (the client rotates
        # past the dead one).
        cli.put("go2", "now")
        out, err = run.communicate(timeout=scaled_timeout(240))
        assert run.returncode == 0, out + err
        # Zero lost steps: each rank ran batches 1..20 exactly once
        # (a re-rendezvous/rollback would repeat a batch number).
        for r in range(2):
            # The runner prefixes forwarded worker lines with
            # "[host:slot]<stdout>", so match by substring.
            batches = [int(line.split("batch=")[1])
                       for line in out.splitlines()
                       if "STEP rank=%d " % r in line]
            assert batches == list(range(1, 21)), (r, batches)
            assert "DONE rank=%d size=2 batch=20" % r in out, out + err
        # ... and no blacklist churn: the failover was invisible to
        # the membership plane.
        assert "blacklisting host" not in err, err
    finally:
        for p in (run, leader_proc, standby_proc):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def test_driver_adoption_restores_world(tmp_path, monkeypatch):
    """Driver crash adoption: a restarted driver pointed at the same
    control journal reconstructs secret/epoch/assignments/blacklist,
    reattaches the still-live workers WITHOUT a world re-formation
    (epoch preserved, no respawn), and books their clean finishes via
    the `finished` notice (no proc handle exists to reap)."""
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner import journal as control_journal
    from horovod_tpu.runner.services import MessageServer

    jdir = str(tmp_path / "ctl")
    slots = [("127.0.0.1", 0), ("127.0.0.1", 1)]

    d1 = ElasticDriver(["true"], FixedHosts({"127.0.0.1": 2}),
                       min_np=2, max_np=2, journal_dir=jdir)
    secret, msg_port = d1._secret, d1._server.port

    # Fake live workers: notification services that answer pings with
    # the journaled secret (what a real WorkerNotificationManager runs).
    fakes = [MessageServer(lambda req: {"ok": True}, secret)
             for _ in slots]
    addrs = {}
    for slot, f in zip(slots, fakes):
        addrs[slot] = ("127.0.0.1", f.start())

    # Publish a world by hand (no real spawns), journal it, crash.
    with d1._lock:
        d1._epoch = 3
        d1._target = list(slots)
        d1._assignments = {s: {"rank": i} for i, s in enumerate(slots)}
        d1._published = True
        d1._port_base = 29600
    for slot, addr in addrs.items():
        d1._worker_addrs.register(slot, addr)
    d1._registry.record_failure("10.9.9.9")  # journaled blacklist
    d1._journal_control()
    _close_driver(d1)
    d1._kv._httpd.journal.close()

    # The restarted driver adopts: journaled secret + message port
    # (workers hold both), old epoch, restored blacklist, external
    # (no-proc-handle) worker bookkeeping.
    monkeypatch.setenv("HOROVOD_CONTROL_RECOVERY_DEADLINE", "15")
    d2 = ElasticDriver(["true"], FixedHosts({"127.0.0.1": 2}),
                       min_np=2, max_np=2, journal_dir=jdir)
    try:
        assert d2._secret == secret
        assert d2._server.port == msg_port
        assert d2._adopt_rec is not None
        assert d2._try_adopt()
        assert d2._epoch == 3 and d2._published
        assert d2._target == slots
        assert set(d2._external) == set(slots)
        assert d2._registry.is_blacklisted("10.9.9.9")
        assert d2._assignments[slots[1]]["rank"] == 1

        # Clean finishes arrive as `finished` notices; the run is then
        # complete with rc=0 and the epoch never bumped.
        for slot in slots:
            resp = d2._handle({"kind": "finished", "host": slot[0],
                               "slot": slot[1], "commit_id": 7})
            assert resp == {"ok": True}
        assert not d2._external
        assert d2._check_procs() is True
        assert d2._rc == 0 and d2._epoch == 3
    finally:
        _close_driver(d2)
        d2._kv._httpd.journal.close()
        for f in fakes:
            f.stop()


def test_driver_adoption_fails_loudly_when_workers_gone(tmp_path,
                                                        monkeypatch):
    """Past HOROVOD_CONTROL_RECOVERY_DEADLINE with a journaled worker
    unreachable, adoption aborts (control_adopt_failed) and the driver
    falls back to ordinary world formation — it must NOT adopt a
    half-dead world silently."""
    from horovod_tpu.common import metrics
    from horovod_tpu.elastic.driver import ElasticDriver

    jdir = str(tmp_path / "ctl")
    d1 = ElasticDriver(["true"], FixedHosts({"127.0.0.1": 1}),
                       min_np=1, max_np=1, journal_dir=jdir)
    with d1._lock:
        d1._epoch = 2
        d1._target = [("127.0.0.1", 0)]
        d1._assignments = {("127.0.0.1", 0): {"rank": 0}}
        d1._published = True
    # A dead notification address: nothing listens there anymore.
    import socket as _socket
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    d1._worker_addrs.register(("127.0.0.1", 0),
                              ("127.0.0.1", dead_port))
    d1._journal_control()
    _close_driver(d1)
    d1._kv._httpd.journal.close()

    monkeypatch.setenv("HOROVOD_CONTROL_RECOVERY_DEADLINE", "0.5")
    d2 = ElasticDriver(["true"], FixedHosts({"127.0.0.1": 1}),
                       min_np=1, max_np=1, journal_dir=jdir)
    try:
        t0 = time.monotonic()
        assert d2._try_adopt() is False
        assert time.monotonic() - t0 < 10.0
        assert not d2._published and d2._epoch == 0
        # The stale journaled address was purged: re-formation starts
        # from a clean notification table.
        assert d2._worker_addrs.get(("127.0.0.1", 0)) is None
    finally:
        _close_driver(d2)
        d2._kv._httpd.journal.close()
