"""Steady-state fast path (frozen negotiated schedules, ISSUE 19).

Unit layer: bucket partitioning, the ScheduleFreezer state machine,
the thaw-hook wiring from the plan-staleness and degraded-route
planes, and the in-process eager engine freezing/thawing end to end
(including the injected ``engine.fastpath.stale_dispatch`` site).
"""

import os

import numpy as np
import pytest

from horovod_tpu.common import metrics
from horovod_tpu.ops import fastpath
from horovod_tpu.ops.fastpath import (
    ScheduleFreezer, bucket_ends, schedule_sig)

PROF = (("allreduce", 0, "float32", 1, 1.0, 1.0, 64),)


def _thaws(reason):
    return metrics.series_sum("fastpath_thaws_total", reason=reason)


# -- bucket partition --------------------------------------------------------

def test_bucket_ends_partition_properties():
    # strictly increasing exclusive ends covering every slot exactly once
    for sizes, buckets, cap in (
            ([100] * 8, 4, 10 ** 9),
            ([1, 1, 1, 10 ** 6], 2, 10 ** 9),
            (list(range(1, 20)), 5, 64),
            ([7], 8, 10 ** 9)):
        ends = bucket_ends(sizes, buckets, cap)
        assert ends[-1] == len(sizes)
        assert ends == sorted(set(ends))
        assert all(e >= 1 for e in ends)


def test_bucket_ends_balances_equal_sizes():
    assert bucket_ends([100] * 8, 4, 10 ** 9) == [2, 4, 6, 8]


def test_bucket_ends_cap_splits_early():
    # every slot above the fusion cap becomes its own bucket even when
    # only one bucket was asked for
    assert bucket_ends([10 ** 7] * 4, 1, 10 ** 6) == [1, 2, 3, 4]


def test_bucket_ends_edges():
    assert bucket_ends([], 4, 1) == []
    assert bucket_ends([5], 1, 10) == [1]
    # more buckets than slots degrades to one slot per bucket
    assert bucket_ends([5, 5], 16, 10 ** 9) == [1, 2]


def test_schedule_sig_stable_and_discriminating():
    assert schedule_sig(PROF) == schedule_sig(tuple(PROF))
    assert schedule_sig(PROF) != schedule_sig(PROF + PROF)
    assert len(schedule_sig(PROF)) == 16


# -- freezer state machine ---------------------------------------------------

def test_freezer_warm_streak_trips_then_freezes():
    fz = ScheduleFreezer(warm_cycles=3, spmd=False, plane_name="t_trip")
    assert not fz.observe(PROF)          # streak 1
    assert not fz.observe(PROF)          # streak 2
    assert fz.observe(PROF)              # streak 3 == warm_cycles: trip
    assert fz.frozen() is None
    assert fz.freeze({"sig": schedule_sig(PROF), "slots": list(PROF)},
                     group_id=7)
    assert fz.frozen() is not None
    assert fz.frozen_group_id() == 7
    # frozen: cycles are no longer counted toward a new streak
    assert not fz.observe(PROF)


def test_freezer_profile_change_resets_streak():
    fz = ScheduleFreezer(warm_cycles=2, spmd=False, plane_name="t_reset")
    assert not fz.observe(PROF)
    other = (("allreduce", 0, "float32", 1, 1.0, 1.0, 128),)
    assert not fz.observe(other)         # different profile: restart
    assert fz.streak == 1                # streak rebuilt from 1
    # an unfreezable cycle (None) zeroes the streak outright
    fz.observe(None)
    assert fz.streak == 0


def test_freezer_refused_freeze_resets_streak():
    fz = ScheduleFreezer(warm_cycles=1, spmd=False, plane_name="t_ref")
    fz.observe(PROF)                     # first sight: streak 1
    assert fz.observe(PROF)              # repeat trips at warm_cycles
    # engine-side eligibility veto (ok=False): stays thawed, re-warms
    assert not fz.freeze({"sig": "x", "slots": []}, group_id=1, ok=False)
    assert fz.frozen() is None
    assert fz.streak == 0


def test_freezer_thaw_is_loud_and_idempotent():
    fz = ScheduleFreezer(warm_cycles=1, spmd=False, plane_name="t_thaw")
    fz.observe(PROF)
    assert fz.observe(PROF)
    assert fz.freeze({"sig": "s", "slots": list(PROF)}, group_id=3)
    before = _thaws("shape")
    frozen_before = metrics.series_sum("fastpath_frozen_cycles_total")
    assert fz.thaw("shape", detail="unit")
    assert fz.frozen() is None and fz.streak == 0
    assert _thaws("shape") == before + 1
    # thawing is not a negotiation cycle nor a frozen one
    assert metrics.series_sum("fastpath_frozen_cycles_total") == \
        frozen_before
    # nothing frozen: no-op, no double count
    assert not fz.thaw("shape", detail="again")
    assert _thaws("shape") == before + 1
    with pytest.raises(ValueError):
        fz.thaw("bogus")


def test_freezer_disabled_never_trips():
    fz = ScheduleFreezer(warm_cycles=1, enabled=False, spmd=False,
                         plane_name="t_off")
    for _ in range(5):
        assert not fz.observe(PROF)
    assert fz.frozen() is None


def test_thaw_callback_runs_under_stage_lock():
    seen = []
    fz = ScheduleFreezer(
        warm_cycles=1, spmd=False, plane_name="t_cb",
        on_thaw=lambda payload, reason: seen.append(
            (payload["sig"], reason)))
    fz.observe(PROF)
    assert fz.observe(PROF)
    assert fz.freeze({"sig": "cb", "slots": []}, group_id=9)
    assert fz.thaw("deadline", detail="unit")
    assert seen == [("cb", "deadline")]


# -- registry + thaw-hook wiring ---------------------------------------------

def _frozen_freezer(name):
    fz = ScheduleFreezer(warm_cycles=1, spmd=False, plane_name=name)
    fz.observe(PROF)
    fz.observe(PROF)
    fz.freeze({"sig": schedule_sig(PROF), "slots": list(PROF)},
              group_id=1)
    return fz


def test_registry_thaw_all_and_describe_schema():
    fastpath.reset()
    try:
        fz = _frozen_freezer("t_reg")
        fastpath.register(fz)
        fastpath.register(fz)  # idempotent
        assert fastpath.thaw_all("deadline", detail="unit") == 1
        assert fz.frozen() is None
        assert fastpath.thaw_all("deadline") == 0  # nothing frozen
        d = fastpath.describe()
        for key in ("frozen_cycles_total", "thaws_total",
                    "thaws_by_reason", "planes"):
            assert key in d, key
        assert set(d["thaws_by_reason"]) <= set(fastpath.THAW_REASONS)
        pl = d["planes"]["t_reg"]
        assert pl["enabled"] is True and pl["frozen"] is False
        assert pl["warm_cycles"] == 1
        fastpath.unregister(fz)
        assert "t_reg" not in fastpath.describe()["planes"]
    finally:
        fastpath.reset()


def test_plan_invalidate_thaws_frozen_schedules():
    # the r17 staleness verdict actuation must thaw (ISSUE 19 wiring)
    from horovod_tpu.utils import plancache
    fastpath.reset()
    try:
        ctl = plancache.PlanController(
            fingerprint="fp-test", plan=None, source=None,
            codec_name="none", hier_available=True, env_pinned=False)
        assert ctl.pin("allreduce", "65536",
                       {"path": "flat", "codec": "none"})
        fz = _frozen_freezer("t_plan")
        fastpath.register(fz)
        before = _thaws("staleness")
        assert ctl.invalidate("allreduce", "65536")
        assert fz.frozen() is None
        assert _thaws("staleness") == before + 1
        # nothing dropped -> no thaw (the hook only fires on real
        # invalidations, so idle staleness sweeps can't churn)
        fz2 = _frozen_freezer("t_plan2")
        fastpath.register(fz2)
        assert not ctl.invalidate("allreduce", "65536")
        assert fz2.frozen() is not None
    finally:
        fastpath.reset()


def test_route_verdict_thaws_frozen_schedules():
    # the r21 demote/promote actuation must thaw (ISSUE 19 wiring)
    from horovod_tpu.common import resilience
    fastpath.reset()
    try:
        fz = _frozen_freezer("t_route")
        fastpath.register(fz)
        before = _thaws("route")
        resilience._apply_route(None, {
            "op": "allreduce", "size_class": "65536",
            "action": "demote", "streak": 2})
        assert fz.frozen() is None
        assert _thaws("route") == before + 1
        # promote thaws too (the route back up is just as loud)
        fz.observe(PROF)
        fz.observe(PROF)
        fz.freeze({"sig": "r", "slots": list(PROF)}, group_id=2)
        resilience._apply_route(None, {
            "op": "allreduce", "size_class": "65536",
            "action": "promote"})
        assert fz.frozen() is None
        assert _thaws("route") == before + 2
    finally:
        fastpath.reset()


def test_exec_cache_stats_counts_hits_and_misses():
    from horovod_tpu.ops.executable_cache import ExecutableCache
    c = ExecutableCache()
    h0, m0 = c.stats()
    assert c.lookup("k") is None                 # miss
    c.put("k", object())
    assert c.lookup("k") is not None             # hit
    h1, m1 = c.stats()
    assert (h1 - h0, m1 - m0) == (1, 1)


# -- in-process eager engine -------------------------------------------------

@pytest.fixture
def fp_world():
    """A fresh single-controller world with a short warm streak; every
    env knob this file touches is restored afterwards."""
    saved = {k: os.environ.get(k) for k in (
        "HOROVOD_FAST_PATH", "HOROVOD_FAST_PATH_WARM_CYCLES",
        "HVD_TPU_FAULT")}
    os.environ.pop("HOROVOD_FAST_PATH", None)
    os.environ["HOROVOD_FAST_PATH_WARM_CYCLES"] = "3"
    import horovod_tpu as hvd
    from horovod_tpu.common import faultline
    faultline.reset()
    hvd.init()
    yield hvd
    hvd.shutdown()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    faultline.reset()


def _allreduce(hvd, n, elems, name):
    out = hvd.allreduce(np.ones((n, elems), np.float32), op=hvd.Sum,
                        name=name)
    np.testing.assert_allclose(np.asarray(out), np.full((elems,), n))


def test_eager_engine_freezes_and_shape_change_thaws(fp_world):
    hvd = fp_world
    n = hvd.size()
    cyc0 = metrics.series_sum("engine_cycles_total")
    fr0 = metrics.series_sum("fastpath_frozen_cycles_total")
    th0 = _thaws("shape")
    for i in range(8):
        _allreduce(hvd, n, 256, "fp.unit.%d" % i)
    d_cyc = metrics.series_sum("engine_cycles_total") - cyc0
    d_fr = metrics.series_sum("fastpath_frozen_cycles_total") - fr0
    # warm_cycles=3: the first 3 ops negotiate; once frozen the rest
    # dispatch from the cached schedule (the freeze lands between the
    # 3rd dispatch and its caller's next enqueue, so at most one extra
    # op slips onto the negotiation path).
    assert fastpath.describe()["planes"]["eager"]["frozen"] is True
    assert d_fr >= 4, (d_cyc, d_fr)
    assert d_cyc <= 4, (d_cyc, d_fr)
    assert d_cyc + d_fr == 8, (d_cyc, d_fr)
    # the overlap bucket histogram observed the frozen dispatches
    snap = metrics.snapshot()["engine_overlap_bucket_seconds"]
    assert sum(s["count"] for s in snap["series"]) >= d_fr
    # a shape change thaws loudly and still computes the right value
    _allreduce(hvd, n, 512, "fp.unit.big")
    assert _thaws("shape") == th0 + 1
    assert fastpath.describe()["planes"]["eager"]["frozen"] is False


def test_eager_engine_stale_dispatch_injection_thaws(fp_world):
    hvd = fp_world
    from horovod_tpu.common import faultline
    n = hvd.size()
    for i in range(6):
        _allreduce(hvd, n, 128, "fp.stale.%d" % i)
    assert fastpath.describe()["planes"]["eager"]["frozen"] is True
    th0 = _thaws("staleness")
    os.environ["HVD_TPU_FAULT"] = \
        "engine.fastpath.stale_dispatch:drop@times=1"
    faultline.reset()
    try:
        # the injected stale dispatch thaws; the staged tensor is
        # flushed back through full negotiation — correct value, no
        # hang
        _allreduce(hvd, n, 128, "fp.stale.inject")
    finally:
        del os.environ["HVD_TPU_FAULT"]
        faultline.reset()
    assert _thaws("staleness") == th0 + 1
    assert fastpath.describe()["planes"]["eager"]["frozen"] is False
    # and the engine re-warms back to frozen afterwards
    for i in range(6):
        _allreduce(hvd, n, 128, "fp.stale.re.%d" % i)
    assert fastpath.describe()["planes"]["eager"]["frozen"] is True


def test_fast_path_env_kill_switch(fp_world):
    # HOROVOD_FAST_PATH=0 read at init: covered via the freezer's
    # enabled flag — here just prove describe() reflects the live knob
    d = fastpath.describe()
    assert d["planes"]["eager"]["enabled"] is True
    assert d["planes"]["eager"]["warm_cycles"] == 3
