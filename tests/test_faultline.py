"""Fault-injection plane unit tests: spec parsing, action semantics,
condition gating, and the C++ hook's env compatibility."""

import os
import subprocess
import sys
import time

import pytest

from horovod_tpu.common import faultline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SITE = "engine.cycle.pre"  # any registered site works for unit tests


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    monkeypatch.delenv("HVD_TPU_FAULT", raising=False)
    faultline.reset()
    yield
    faultline.reset()


# -- parsing ---------------------------------------------------------------

def test_parse_multiple_specs_with_args_and_conditions():
    specs = faultline.parse(
        "%s:delay:0.5@rank=1, elastic.state.commit:die:17@host=h@epoch=2"
        % SITE)
    assert specs[SITE].action == "delay"
    assert specs[SITE].arg == 0.5
    assert specs[SITE].conds == (("rank", "1"),)
    die = specs["elastic.state.commit"]
    assert die.action == "die" and die.arg == 17.0
    assert die.conds == (("host", "h"), ("epoch", "2"))


def test_parse_defaults_per_action():
    specs = faultline.parse("%s:delay,mh.drain.record:drop" % SITE)
    assert specs[SITE].arg == 0.25
    assert specs["mh.drain.record"].arg == 0.0


@pytest.mark.parametrize("bad", [
    "nope.unknown:delay",          # unknown site
    "%s:explode" % SITE,           # unknown action
    "%s" % SITE,                   # missing action
    "%s:delay:abc" % SITE,         # non-numeric arg
    "%s:delay@color=red" % SITE,   # unknown condition key
    "%s:delay,%s:delay" % (SITE, SITE),  # armed twice
    "%s:drop" % SITE,              # drop at a site without skip
])
def test_parse_is_strict(bad):
    with pytest.raises(ValueError):
        faultline.parse(bad)


def test_site_requires_registration():
    with pytest.raises(KeyError):
        faultline.site("never.registered")


# -- firing ----------------------------------------------------------------

def test_unarmed_site_is_a_noop():
    assert faultline.site(SITE) is False


DROP_SITE = "mh.drain.record"  # a site whose plant honors drop


def test_drop_returns_true(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop" % DROP_SITE)
    assert faultline.site(DROP_SITE) is True


def test_preemption_and_durability_sites_parse():
    # ISSUE 5 sites: all three are drop-capable (synthetic preemption
    # arrival / lost drain ack / torn spill write) and compose with
    # the targeting + counting keys the drain e2e tests arm.
    specs = faultline.parse(
        "worker.preempt.sigterm:drop@host=h@epoch=1@after=2@times=1,"
        "driver.drain.ack:drop,elastic.state.spill:drop@times=1")
    pre = specs["worker.preempt.sigterm"]
    assert pre.action == "drop" and pre.after == 2 and pre.times == 1
    assert pre.conds == (("host", "h"), ("epoch", "1"))
    assert specs["driver.drain.ack"].action == "drop"
    assert specs["elastic.state.spill"].times == 1


def test_delay_sleeps(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:delay:0.2" % SITE)
    t0 = time.monotonic()
    assert faultline.site(SITE) is False
    assert time.monotonic() - t0 >= 0.2


def test_condition_gates_by_env(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop@rank=1" % DROP_SITE)
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    # unset env: condition unmet
    assert faultline.site(DROP_SITE) is False
    monkeypatch.setenv("HOROVOD_RANK", "0")
    assert faultline.site(DROP_SITE) is False
    monkeypatch.setenv("HOROVOD_RANK", "1")
    assert faultline.site(DROP_SITE) is True


def test_times_bounds_fires(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop@times=2" % DROP_SITE)
    assert [faultline.site(DROP_SITE) for _ in range(4)] == [
        True, True, False, False]


def test_after_skips_then_fires(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop@after=2" % DROP_SITE)
    assert [faultline.site(DROP_SITE) for _ in range(4)] == [
        False, False, True, True]


def test_after_and_times_window(monkeypatch):
    # healthy, then flaky, then healthy again — the drop-and-recover
    # shape the self-healing tests arm.
    monkeypatch.setenv("HVD_TPU_FAULT",
                       "%s:drop@after=1@times=2" % DROP_SITE)
    assert [faultline.site(DROP_SITE) for _ in range(5)] == [
        False, True, True, False, False]


def test_counting_keys_compose_with_env_conditions(monkeypatch):
    # Ineligible calls (condition unmet) must not consume the window.
    monkeypatch.setenv("HVD_TPU_FAULT",
                       "%s:drop@rank=1@times=1" % DROP_SITE)
    monkeypatch.setenv("HOROVOD_RANK", "0")
    assert faultline.site(DROP_SITE) is False
    monkeypatch.setenv("HOROVOD_RANK", "1")
    assert faultline.site(DROP_SITE) is True
    assert faultline.site(DROP_SITE) is False  # window consumed


def test_rearm_resets_fire_counters(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop@times=1" % DROP_SITE)
    assert faultline.site(DROP_SITE) is True
    assert faultline.site(DROP_SITE) is False
    # A changed env value is a new experiment: counters restart.
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop@times=1 " % DROP_SITE)
    assert faultline.site(DROP_SITE) is True


@pytest.mark.parametrize("bad", [
    "%s:drop@times=x" % DROP_SITE,
    "%s:drop@times=-1" % DROP_SITE,
    "%s:drop@after=nope" % DROP_SITE,
])
def test_counting_keys_parse_strictly(bad):
    with pytest.raises(ValueError):
        faultline.parse(bad)


def test_rearm_within_one_process(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "%s:drop" % DROP_SITE)
    assert faultline.site(DROP_SITE) is True
    monkeypatch.delenv("HVD_TPU_FAULT")
    assert faultline.site(DROP_SITE) is False  # env change re-parses


def test_die_exits_the_process():
    proc = subprocess.run(
        [sys.executable, "-c",
         "from horovod_tpu.common import faultline\n"
         "faultline.site('%s')\n"
         "print('UNREACHED')" % SITE],
        env=dict(os.environ, HVD_TPU_FAULT="%s:die:17" % SITE,
                 PYTHONPATH=REPO),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 17, proc.stdout + proc.stderr
    assert "UNREACHED" not in proc.stdout


# -- the C++ hook parses the same env --------------------------------------

def test_cpp_hook_die_action(tmp_path):
    """fault::Point in the native core honors the same spec syntax:
    arm core.enqueue.pre_insert with die and the first enqueue kills
    the process with the spec's exit code."""
    from horovod_tpu.core.client import core_library_available
    if not core_library_available():
        pytest.skip("native core unavailable")
    script = (
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "hvd.init(controller='tcp')\n"
        "hvd.allreduce(np.ones(2, np.float32), name='x')\n"
        "print('UNREACHED')\n")
    worker = tmp_path / "worker.py"
    worker.write_text(script)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               HOROVOD_RANK="0", HOROVOD_SIZE="1",
               HOROVOD_PORT_BASE="28911",
               HVD_TPU_FAULT="core.enqueue.pre_insert:die:19")
    proc = subprocess.run([sys.executable, str(worker)], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 19, proc.stdout + proc.stderr
    assert "UNREACHED" not in proc.stdout
