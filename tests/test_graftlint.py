"""graftlint self-tests: the real-tree zero-findings baseline (this is
the tier-1 gate the CI line mirrors) plus positive/negative fixtures
per rule under ``tests/graftlint_fixtures/``.

The fixture configs aim every rule at the fixture tree via
``LintConfig`` overrides, so these tests are hermetic: they neither
depend on nor mutate the live annotations.
"""

import os
import subprocess
import sys

from graftlint.core import LintConfig, run_paths
from graftlint.rules import ALL_CHECKS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "graftlint_fixtures")


def _checks(findings):
    return [f.check for f in findings]


def _fmt(findings):
    return "\n".join(f.render(REPO) for f in findings)


def _ownership_cfg(*names):
    """Config aiming ONLY the ownership rule at fixture files."""
    return LintConfig(
        repo_root=FIX,
        ownership_files=tuple(os.path.join("ownership", n) for n in names),
        config_file="absent/config.py", doc_files=(),
        env_scan_root="absent", hot_path_roots=())


def _run_ownership(*names):
    cfg = _ownership_cfg(*names)
    return run_paths([os.path.join(FIX, "ownership", n) for n in names],
                     cfg)


# -- the baseline gate -----------------------------------------------------

def test_real_tree_zero_findings():
    """The acceptance bar: the live tree lints clean.  Reverting the
    compile_notify fix (or any annotated invariant) fails THIS test —
    dispatch_pos.py mirrors the exact reverted shape the ownership
    rule would flag."""
    findings = run_paths([os.path.join(REPO, "horovod_tpu")],
                         LintConfig(repo_root=REPO))
    assert findings == [], "graftlint must be clean on the real tree:\n" \
        + _fmt(findings)


def test_cli_exits_zero_on_real_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "graftlint"], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_lists_every_rule():
    proc = subprocess.run(
        [sys.executable, "-m", "graftlint", "--list-rules"], cwd=REPO,
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    for check, _desc in ALL_CHECKS:
        assert check in proc.stdout


# -- ownership / lock discipline -------------------------------------------

def test_ownership_shared_flags_unannotated_shared_attr():
    findings = _run_ownership("own_pos.py")
    assert "ownership-shared" in _checks(findings), _fmt(findings)


def test_ownership_shared_passes_annotated_locked_attr():
    assert _run_ownership("own_neg.py") == []


def test_lock_discipline_flags_unlocked_write():
    findings = _run_ownership("lock_pos.py")
    assert _checks(findings) == ["lock-discipline"], _fmt(findings)


def test_lock_discipline_accepts_condition_alias_and_requires_lock():
    assert _run_ownership("lock_neg.py") == []


def test_owned_by_flags_foreign_thread_read():
    findings = _run_ownership("owned_pos.py")
    assert "owned-by" in _checks(findings), _fmt(findings)


def test_owned_by_passes_owner_only_access():
    assert _run_ownership("owned_neg.py") == []


def test_dispatch_scoped_flags_reverted_compile_notify_pattern():
    """dispatch_pos.py is the compile_notify revert, verbatim in shape:
    per-dispatch callback parked on the shared mesh object."""
    findings = _run_ownership("dispatch_pos.py")
    assert _checks(findings) == ["dispatch-scoped"], _fmt(findings)
    assert "compile_notify" in findings[0].message


def test_dispatch_scoped_passes_threaded_callback():
    assert _run_ownership("dispatch_neg.py") == []


# -- env drift -------------------------------------------------------------

def _env_cfg(which):
    root = os.path.join(FIX, which)
    return root, LintConfig(
        repo_root=root, ownership_files=(), config_file="config.py",
        doc_files=("docs.md",), env_scan_root="scan", hot_path_roots=())


def test_env_drift_flags_undocumented_duplicate_and_conflict():
    root, cfg = _env_cfg("env_pos")
    checks = _checks(run_paths([root], cfg))
    assert "env-undocumented" in checks      # GHOST_KNOB
    assert "env-duplicate-read" in checks    # FUSION_THRESHOLD twice
    assert "env-default-conflict" in checks  # PING_TIMEOUT 600 vs 900


def test_env_drift_passes_documented_single_reads():
    root, cfg = _env_cfg("env_neg")
    findings = run_paths([root], cfg)
    # "600" (str) vs 600 (int) must compare numerically equal, and the
    # HVD_TPU_ alias form counts as documentation.
    assert findings == [], _fmt(findings)


# -- host bounce -----------------------------------------------------------

def _hot_cfg(name):
    return LintConfig(
        repo_root=FIX, ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="absent",
        hot_path_roots=(os.path.join("hot", name),))


def test_host_bounce_flags_np_item_and_device_get():
    findings = run_paths([os.path.join(FIX, "hot", "hot_pos.py")],
                         _hot_cfg("hot_pos.py"))
    assert _checks(findings) == ["host-bounce"] * 3, _fmt(findings)


def test_host_bounce_passes_metadata_and_cited_suppression():
    findings = run_paths([os.path.join(FIX, "hot", "hot_neg.py")],
                         _hot_cfg("hot_neg.py"))
    assert findings == [], _fmt(findings)


# -- suppression / annotation hygiene --------------------------------------

def _hygiene_cfg(name, ownership=False):
    return LintConfig(
        repo_root=FIX,
        ownership_files=((os.path.join("hygiene", name),)
                         if ownership else ()),
        config_file="absent/config.py", doc_files=(),
        env_scan_root="absent",
        hot_path_roots=(() if ownership
                        else (os.path.join("hygiene", name),)))


def test_suppression_without_issue_is_a_finding():
    findings = run_paths([os.path.join(FIX, "hygiene", "bad_sup.py")],
                         _hygiene_cfg("bad_sup.py"))
    checks = _checks(findings)
    assert "bad-suppression" in checks, _fmt(findings)
    # The uncited suppression still silences host-bounce on its line;
    # what remains is the citation violation itself.
    assert "host-bounce" not in checks


def test_unused_suppression_is_a_finding():
    findings = run_paths([os.path.join(FIX, "hygiene", "unused_sup.py")],
                         _hygiene_cfg("unused_sup.py"))
    assert _checks(findings) == ["unused-suppression"], _fmt(findings)


def test_unknown_key_and_dangling_annotation_are_findings():
    findings = run_paths([os.path.join(FIX, "hygiene", "bad_ann.py")],
                         _hygiene_cfg("bad_ann.py", ownership=True))
    checks = _checks(findings)
    assert checks.count("bad-annotation") == 2, _fmt(findings)


def test_scoped_run_does_not_flag_out_of_scope_suppressions():
    """A narrowed run (only the ownership fixtures) must not call the
    hot-path suppressions in hygiene/ 'unused' — their check never ran
    there."""
    findings = _run_ownership("own_neg.py")
    assert findings == [], _fmt(findings)


# -- faultline site registry ------------------------------------------------

def _faultline_cfg(variant):
    base = os.path.join("faultline", variant)
    return LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(os.path.join(base, "docs.md"),),
        env_scan_root="absent", hot_path_roots=(),
        faultline_module=os.path.join(base, "faultline.py"),
        faultline_roots=(os.path.join(base, "tree"),),
        faultline_cc_roots=(os.path.join(base, "cc"),))


def _run_faultline(variant):
    return run_paths([os.path.join(FIX, "faultline", variant)],
                     _faultline_cfg(variant))


def test_faultline_registered_documented_unique_is_clean():
    """Guard + fire at one seam (armed()/fault::Armed + site()/
    fault::Point) is the canonical pattern, not a duplicate."""
    findings = _run_faultline("ok")
    assert findings == [], _fmt(findings)


def test_faultline_flags_unregistered_site_in_both_languages():
    checks = _checks(_run_faultline("pos"))
    # zz.unregistered (python) + cc.unregistered (native core)
    assert checks.count("fault-site-unregistered") == 2, \
        _fmt(_run_faultline("pos"))


def test_faultline_flags_duplicate_fire():
    findings = _run_faultline("pos")
    dups = [f for f in findings if f.check == "fault-site-duplicate"]
    assert len(dups) == 1 and "a.one" in dups[0].message, _fmt(findings)


def test_faultline_flags_undocumented_registered_site():
    findings = _run_faultline("pos")
    undoc = [f for f in findings
             if f.check == "fault-site-undocumented"]
    assert len(undoc) == 1 and "u.undoc" in undoc[0].message, \
        _fmt(findings)


def test_faultline_flags_orphan_registered_site():
    findings = _run_faultline("pos")
    orphans = [f for f in findings if f.check == "fault-site-orphan"]
    assert len(orphans) == 1 and "d.orphan" in orphans[0].message, \
        _fmt(findings)


def test_faultline_real_tree_registry_matches_runtime_table():
    """The rule parses SITES statically; the runtime module must agree
    (a drift here means the lint is checking a different table than
    the one HVD_TPU_FAULT validates against)."""
    from graftlint.rules.faultline_sites import registry_sites
    from horovod_tpu.common import faultline as fl
    parsed = registry_sites(
        os.path.join(REPO, "horovod_tpu", "common", "faultline.py"))
    assert set(parsed) == set(fl.SITES)


# -- metric series-name registry -------------------------------------------

def _metrics_cfg(variant):
    base = os.path.join("metrics", variant)
    return LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="absent", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(),
        metrics_module=os.path.join(base, "metrics.py"),
        metrics_roots=(base,),
        bootstrap_env_files=())


def _run_metrics(variant):
    return run_paths([os.path.join(FIX, "metrics", variant)],
                     _metrics_cfg(variant))


def test_metric_names_clean_fixture():
    """Registered names used with their declared kinds (including the
    registry module's own bare-call plants) lint clean."""
    findings = _run_metrics("ok")
    assert findings == [], _fmt(findings)


def test_metric_names_flags_unregistered_and_nonliteral():
    findings = [f for f in _run_metrics("pos")
                if f.check == "metric-unregistered"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 2, _fmt(_run_metrics("pos"))
    assert "nope_total" in msgs and "not a string literal" in msgs


def test_metric_names_flags_kind_mismatch():
    findings = [f for f in _run_metrics("pos")
                if f.check == "metric-kind-mismatch"]
    assert len(findings) == 1 and "x_total" in findings[0].message, \
        _fmt(_run_metrics("pos"))


def test_metric_names_flags_duplicate_declaration():
    findings = [f for f in _run_metrics("pos")
                if f.check == "metric-duplicate-decl"]
    assert len(findings) == 1 and "dup_total" in findings[0].message, \
        _fmt(_run_metrics("pos"))


def test_metric_names_flags_orphan_declaration():
    findings = [f for f in _run_metrics("pos")
                if f.check == "metric-orphan"]
    assert len(findings) == 1 and "orphan_total" in findings[0].message, \
        _fmt(_run_metrics("pos"))


def test_metric_real_tree_registry_matches_runtime_table():
    """The rule parses NAMES statically; the runtime registry must
    agree, and every declared kind must be one the registry
    implements."""
    from graftlint.rules.metric_names import registry_names
    from horovod_tpu.common import metrics as m
    parsed, dup_findings = registry_names(
        os.path.join(REPO, "horovod_tpu", "common", "metrics.py"))
    assert dup_findings == []
    assert set(parsed) == set(m.NAMES)
    assert {kind for kind, _ in parsed.values()} <= {
        "counter", "gauge", "histogram"}


# -- env-drift: bootstrap-module registration ------------------------------

def test_env_drift_flags_undocumented_bootstrap_knobs():
    """envutil helper reads AND direct os.environ gets in a registered
    bootstrap module must be documented; foreign-prefix reads are out
    of scope."""
    cfg = LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(os.path.join("env_boot", "docs.md"),),
        env_scan_root="env_boot", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(), metrics_roots=(),
        metrics_module="absent/metrics.py",
        bootstrap_env_files=(os.path.join("env_boot", "mod.py"),))
    findings = [f for f in run_paths([os.path.join(FIX, "env_boot")], cfg)
                if f.check == "env-undocumented"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 2, msgs
    assert "HOROVOD_BOOT_MISSING" in msgs
    assert "HOROVOD_BOOT_RAW_MISSING" in msgs
    assert "HOROVOD_BOOT_DOCUMENTED" not in msgs


# -- spmd-uniform: rank-taint dataflow --------------------------------------

def _spmd_cfg(*names):
    return LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="absent", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(), metrics_module="absent/metrics.py",
        metrics_roots=(), bootstrap_env_files=(),
        harness_env_files=(),
        spmd_roots=tuple(os.path.join("spmd", n) for n in names),
        cpp_lock_roots=())


def _run_spmd(*names):
    return run_paths([os.path.join(FIX, "spmd", n) for n in names],
                     _spmd_cfg(*names))


def test_spmd_uniform_flags_every_seeded_shape():
    """route_pos seeds every source/flow shape: filesystem blob into
    the controller (the r14 reconstruction), per-rank env through a
    helper call (interprocedural), a rank() keyword arg through a
    routing helper, wall-clock into a schedule lever, and
    set-iteration order into a published plan."""
    findings = _run_spmd("route_pos.py")
    assert _checks(findings) == ["spmd-uniform"] * 6, _fmt(findings)
    msgs = "\n".join(f.message for f in findings)
    assert "filesystem read (open)" in msgs
    assert "per-rank env HOROVOD_TENANT_ID" in msgs
    assert "time.monotonic()" in msgs
    assert "set-iteration-order" in msgs
    assert "_route_via() [which routes it to route()]" in msgs
    assert "gate_in_condition" in msgs  # sink inside an if-test


def test_spmd_uniform_r14_reconstruction_names_the_routing_sink():
    """The r14 bug shape — a member routing by its own per-host blob
    with no KV agreement — is reported AT the controller construction,
    naming the divergence source."""
    findings = [f for f in _run_spmd("route_pos.py")
                if "filesystem" in f.message]
    assert len(findings) == 1, _fmt(findings)
    assert "PlanController()" in findings[0].message
    assert "adopt_local" in findings[0].message


def test_spmd_uniform_barriers_and_sorted_iteration_are_clean():
    """Declared barriers (def-level and call-line), sorted() over a
    set, and rank-gated DATA (explicit flows only) all lint clean —
    and the barrier annotations are not called dangling."""
    findings = _run_spmd("route_neg.py")
    assert findings == [], _fmt(findings)


def test_spmd_uniform_cited_suppression_is_clean_and_used():
    findings = _run_spmd("route_sup.py")
    assert findings == [], _fmt(findings)


# -- cpp-guarded-by / cpp-requires / cpp-excludes ---------------------------

def _cpp_cfg(variant):
    return LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="absent", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(), metrics_module="absent/metrics.py",
        metrics_roots=(), bootstrap_env_files=(),
        harness_env_files=(), spmd_roots=(),
        cpp_lock_roots=(os.path.join("cpp", variant),))


def _run_cpp(variant):
    return run_paths([os.path.join(FIX, "cpp", variant)],
                     _cpp_cfg(variant))


def test_cpp_rules_flag_configure_shape_requires_and_excludes():
    """tuner.cc mirrors the live-tree ParameterManager::Configure fix:
    reverting that fix re-creates exactly the unlocked-write +
    unlocked-REQUIRES-call shape seeded here.  Flush exercises a
    STACKED annotation (REQUIRES + EXCLUDES on one declaration — both
    contracts must parse) and Configure plants a C++14 digit separator
    in front of the violations (the stripper must not eat them)."""
    findings = _run_cpp("pos")
    checks = _checks(findings)
    assert checks.count("cpp-guarded-by") == 1, _fmt(findings)
    assert checks.count("cpp-requires") == 2, _fmt(findings)
    assert checks.count("cpp-excludes") == 2, _fmt(findings)
    msgs = "\n".join(f.message for f in findings)
    assert "Configure" in msgs and "value_" in msgs
    assert "GUARDED_BY(mu_)" in msgs
    assert "Publish() [EXCLUDES(io_mu_)]" in msgs
    # Both stacked contracts survive: Reset (neither lock held) trips
    # the REQUIRES side of the same declaration.
    assert "Reset calls Publish() [REQUIRES(mu_)]" in msgs


def test_cpp_rules_locked_requires_and_cited_suppression_are_clean():
    findings = _run_cpp("neg")
    assert findings == [], _fmt(findings)


# -- env-drift: harness pins ------------------------------------------------

def test_env_harness_pin_flags_ghost_pin_only():
    """Dict-literal and subscript pins of HOROVOD_*/HVD_TPU_* keys in a
    registered harness must be documented; plain env READS are not
    pins."""
    cfg = LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="harness", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(), metrics_module="absent/metrics.py",
        metrics_roots=(), bootstrap_env_files=(),
        harness_env_files=(os.path.join("harness", "harness.py"),),
        harness_doc_files=(os.path.join("harness", "docs.md"),),
        spmd_roots=(), cpp_lock_roots=())
    findings = [f for f in run_paths([os.path.join(FIX, "harness")],
                                     cfg)
                if f.check == "env-harness-pin"]
    assert len(findings) == 1, _fmt(findings)
    assert "HOROVOD_GHOST_PIN" in findings[0].message
    assert "DOCUMENTED_PIN" not in _fmt(findings)


def test_spawn_harness_pins_documented_in_tests_readme():
    """The real harness's pin set is exactly what tests/README.md
    documents (a new undocumented pin fails the real-tree baseline,
    which is how the HOROVOD_CYCLE_TIME warm-start suppression should
    have been caught)."""
    from graftlint.rules.env_drift import harness_pins
    pins = {k for k, _ in harness_pins(
        os.path.join(REPO, "tests", "utils", "spawn.py"))}
    assert pins == {"HOROVOD_RANK", "HOROVOD_SIZE",
                    "HOROVOD_PORT_BASE", "HOROVOD_CYCLE_TIME"}


# -- machine-readable output ------------------------------------------------

def test_cli_json_zero_findings_shape(capsys):
    """`python -m graftlint --json` emits one JSON object with
    repo-relative findings; the real tree is the committed
    zero-findings baseline."""
    import json

    from graftlint.__main__ import main
    rc = main(["--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["count"] == 0 and data["findings"] == []
    assert data["paths"] == ["horovod_tpu"]


# -- collective-schedule / lock-cycle ---------------------------------------

def _sched_cfg(sched=(), locks=()):
    return LintConfig(
        repo_root=FIX,
        ownership_files=(), config_file="absent/config.py",
        doc_files=(), env_scan_root="absent", hot_path_roots=(),
        faultline_module="absent/faultline.py", faultline_roots=(),
        faultline_cc_roots=(), metrics_module="absent/metrics.py",
        metrics_roots=(), bootstrap_env_files=(),
        harness_env_files=(), spmd_roots=(), cpp_lock_roots=(),
        schedule_roots=tuple(os.path.join("schedule", n)
                             for n in sched),
        schedule_cc_roots=(), lock_cycle_cc_roots=(),
        lock_cycle_roots=tuple(os.path.join("schedule", n)
                               for n in locks))


def _run_sched(name, **kw):
    return run_paths([os.path.join(FIX, "schedule", name)],
                     _sched_cfg(**kw))


def test_schedule_flags_every_deadlock_and_divergence_shape():
    """One finding per seeded hazard: arm-skip, arm-reorder, tainted
    trip count, set iteration, taint through a local, and taint
    through a helper's return value."""
    findings = _run_sched("sched_pos.py", sched=("sched_pos.py",))
    checks = _checks(findings)
    assert checks.count("collective-tainted-branch") == 4, _fmt(findings)
    assert checks.count("collective-order-divergence") == 2, \
        _fmt(findings)
    msgs = "\n".join(f.message for f in findings)
    assert "tainted_skip" in msgs and "tainted_order" in msgs
    assert "tainted_trip_count" in msgs and "set_iteration" in msgs
    assert "taint_through_local" in msgs
    assert "taint_interprocedural" in msgs


def test_schedule_passes_uniform_barriers_and_exemptions():
    """Data-conditioned branches, collective-result barriers,
    spmd-uniform waivers, order exemptions, and sorted() fan-out all
    lint clean."""
    findings = _run_sched("sched_neg.py", sched=("sched_neg.py",))
    assert findings == [], _fmt(findings)


def test_lock_cycles_flags_lexical_and_interprocedural_inversion():
    findings = _run_sched("locks_pos.py", locks=("locks_pos.py",))
    assert _checks(findings) == ["lock-cycle", "lock-cycle"], \
        _fmt(findings)
    msgs = "\n".join(f.message for f in findings)
    assert "Inverted._a -> Inverted._b" in msgs
    assert "Caller._mu" in msgs and "_registry_lock" in msgs


def test_lock_cycles_passes_global_order_and_condition_alias():
    findings = _run_sched("locks_neg.py", locks=("locks_neg.py",))
    assert findings == [], _fmt(findings)


# -- schedule-determinism certificate ---------------------------------------

def _fixture_cert():
    from graftlint.core import reset_cache
    from graftlint.rules import collective_schedule
    cfg = _sched_cfg(sched=("sched_neg.py",))
    reset_cache()
    run_paths([os.path.join(FIX, "schedule", "sched_neg.py")], cfg)
    return collective_schedule.build_certificate(cfg)


def test_certificate_fixture_golden():
    """The fixture entry's certificate: collapsed branch (both arms
    issue the same allreduce), the barrier, then the spliced sorted
    fan-out loop."""
    cert = _fixture_cert()
    assert cert["format"] == "hvd-tpu-schedule-cert/1"
    (entry,) = cert["planes"]["fixture"]
    assert entry["entry"] == "data_conditioned"
    assert entry["signature"] == "allreduce;barrier;(allreduce)*"
    sites = [op["site"] for op in _flat_ops(entry["schedule"])]
    assert all(s.startswith("schedule/sched_neg.py:") for s in sites)


def _flat_ops(node):
    if "op" in node:
        return [node]
    for key in ("seq", "alt"):
        if key in node:
            return [o for child in node[key] for o in _flat_ops(child)]
    return _flat_ops(node["loop"]) if "loop" in node else []


def test_certificate_is_deterministic():
    """Byte-identical certificates across two full runs — the property
    CI relies on to diff certs between commits."""
    import json
    a = json.dumps(_fixture_cert(), sort_keys=True)
    b = json.dumps(_fixture_cert(), sort_keys=True)
    assert a == b


def test_certificate_real_tree_covers_required_planes():
    """Acceptance bar from the r19 issue: the live tree's cert lists
    the per-cycle collective sequence for the eager, hier, and ZeRO
    planes, plus the native enqueue/negotiate sites."""
    from graftlint.core import reset_cache
    from graftlint.rules import collective_schedule
    cfg = LintConfig(repo_root=REPO)
    reset_cache()
    run_paths([os.path.join(REPO, "horovod_tpu")], cfg)
    cert = collective_schedule.build_certificate(cfg)
    for plane in ("eager", "hier", "zero1", "zero2", "zero3"):
        assert plane in cert["planes"], sorted(cert["planes"])
        (entry,) = cert["planes"][plane]
        assert entry["signature"], plane
    ops = [s["op"] for s in
           cert["native_sites"]["horovod_tpu/core/src/operations.cc"]]
    assert "negotiate" in ops and "execute" in ops
