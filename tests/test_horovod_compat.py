"""The drop-in `horovod` import alias (docs/migration.md): reference
scripts keep their import lines unchanged and get the horovod_tpu
implementations — the SAME module objects, not copies."""

import importlib


def test_adapter_imports_are_the_same_modules():
    import horovod.torch as compat_torch

    import horovod_tpu.torch as real_torch
    assert compat_torch is real_torch

    import horovod.tensorflow as compat_tf

    import horovod_tpu.tensorflow as real_tf
    assert compat_tf is real_tf

    # Upstream spelling horovod.tensorflow.keras -> the keras adapter.
    compat_tfk = importlib.import_module("horovod.tensorflow.keras")
    import horovod_tpu.keras as real_keras
    assert compat_tfk is real_keras


def test_nested_and_platform_imports():
    import horovod.spark.keras as compat_sk

    import horovod_tpu.spark.keras as real_sk
    assert compat_sk is real_sk

    import horovod.ray as compat_ray

    import horovod_tpu.ray as real_ray
    assert compat_ray is real_ray

    import horovod.elastic as compat_elastic

    import horovod_tpu.elastic as real_elastic
    assert compat_elastic is real_elastic


def test_top_level_surface():
    import horovod

    from horovod_tpu.runner.run_api import run as real_run
    assert horovod.run is real_run
    # Attribute access routes like imports do.
    import horovod_tpu.spark
    assert horovod.spark is horovod_tpu.spark


def test_unknown_submodule_raises_cleanly():
    import pytest
    with pytest.raises(ImportError):
        importlib.import_module("horovod.does_not_exist")
