"""JAX adapter e2e tests: DistributedOptimizer / tape / broadcast /
compression / sync batch norm (reference: test/parallel/test_torch.py
optimizer + broadcast cases and the pytorch_mnist example config)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu.jax as hvd

SIZE = 8


def _toy_problem(seed=0):
    """Linear-regression 'MNIST stand-in': learn W from noisy samples."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(10, 4).astype(np.float32)
    x = rng.randn(SIZE * 16, 10).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(SIZE * 16, 4).astype(np.float32)
    params = {"w": jnp.zeros((10, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, {"x": x, "y": y}, loss_fn, w_true


def test_data_parallel_step_trains(hvd_world):
    params, batch, loss_fn, w_true = _toy_problem()
    step, init = hvd.make_data_parallel_step(loss_fn, optax.sgd(0.1))
    params = hvd.broadcast_parameters(params)
    opt_state = hvd.replicate(init(params))
    sharded = hvd.shard_batch(batch)
    losses = []
    for _ in range(150):
        params, opt_state, loss = step(params, opt_state, sharded)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05
    np.testing.assert_allclose(np.asarray(params["w"]), w_true, atol=0.15)


def test_sharded_jit_step_matches_shard_map(hvd_world):
    params, batch, loss_fn, _ = _toy_problem(seed=1)
    step_a, init_a = hvd.make_data_parallel_step(loss_fn, optax.sgd(0.05))
    step_b, init_b = hvd.make_sharded_jit_step(loss_fn, optax.sgd(0.05))
    # Copy before broadcast: both steps donate their inputs, so they must
    # not share buffers.
    pa = hvd.broadcast_parameters(jax.tree.map(jnp.copy, params))
    pb = hvd.broadcast_parameters(jax.tree.map(jnp.copy, params))
    sa = hvd.replicate(init_a(pa))
    sb = hvd.replicate(init_b(pb))
    batch_sharded = hvd.shard_batch(batch)
    for _ in range(5):
        pa, sa, la = step_a(pa, sa, batch_sharded)
        pb, sb, lb = step_b(pb, sb, batch_sharded)
    # Same math, two lowerings: explicit psum vs compiler-inserted.
    np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pb["w"]),
                               rtol=1e-4, atol=1e-5)


def test_distributed_optimizer_compression(hvd_world):
    params, batch, loss_fn, _ = _toy_problem(seed=2)
    step, init = hvd.make_data_parallel_step(
        loss_fn, optax.sgd(0.1), compression=hvd.Compression.bf16)
    params = hvd.broadcast_parameters(params)
    opt_state = hvd.replicate(init(params))
    sharded = hvd.shard_batch(batch)
    l0 = None
    for i in range(30):
        params, opt_state, loss = step(params, opt_state, sharded)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0


def test_backward_passes_per_step(hvd_world):
    params, batch, loss_fn, _ = _toy_problem(seed=3)
    step, init = hvd.make_data_parallel_step(
        loss_fn, optax.sgd(0.1), backward_passes_per_step=2)
    params = hvd.broadcast_parameters(params)
    opt_state = hvd.replicate(init(params))
    sharded = hvd.shard_batch(batch)
    p0 = np.asarray(params["w"]).copy()
    params, opt_state, _ = step(params, opt_state, sharded)
    # First call only accumulates: params unchanged.
    np.testing.assert_allclose(np.asarray(params["w"]), p0)
    params, opt_state, _ = step(params, opt_state, sharded)
    assert not np.allclose(np.asarray(params["w"]), p0)


def test_distributed_gradient_tape(hvd_world):
    params, batch, loss_fn, _ = _toy_problem(seed=4)
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hvd",))
    tape = hvd.DistributedGradientTape(loss_fn)

    from jax.sharding import PartitionSpec as P
    def step(params, batch):
        loss, grads = tape.gradient(params, batch)
        return jax.lax.pmean(loss, "hvd"), grads

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P("hvd")),
        out_specs=(P(), P()), check_vma=False))
    sharded = hvd.shard_batch(batch)
    loss, grads = f(params, sharded)
    # Hand-computed global gradient equals the tape's averaged gradient.
    expected = jax.grad(loss_fn)(params, batch)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(expected["w"]), rtol=1e-4,
                               atol=1e-5)


def test_broadcast_object_and_allgather_object(hvd_world):
    obj = {"epoch": 3, "lr": 0.01, "name": "résnet"}
    out = hvd.broadcast_object(obj, root_rank=0)
    assert out == obj
    gathered = hvd.allgather_object(obj)
    assert len(gathered) == SIZE and gathered[0] == obj


def test_broadcast_optimizer_state(hvd_world):
    params = {"w": jnp.ones((3, 3))}
    opt = optax.adam(1e-3)
    state = opt.init(params)
    out = hvd.broadcast_optimizer_state(state)
    chex_leaves = jax.tree.leaves(out)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in chex_leaves)


def test_sync_batch_norm_stats(hvd_world):
    rng = np.random.RandomState(0)
    x = rng.randn(SIZE * 4, 6).astype(np.float32)
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hvd",))
    from jax.sharding import PartitionSpec as P
    f = jax.jit(jax.shard_map(
        lambda s: hvd.sync_batch_norm_stats(s), mesh=mesh,
        in_specs=P("hvd"), out_specs=P(), check_vma=False))
    mean, var = f(x)
    np.testing.assert_allclose(mean, x.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(var, x.var(0), rtol=1e-3, atol=1e-4)


def test_metric_average(hvd_world):
    assert hvd.metric_average(3.0, "acc") == pytest.approx(3.0)


def test_hierarchical_allreduce_matches_flat(hvd_world):
    # The reference's HOROVOD_HIERARCHICAL_ALLREDUCE as mesh
    # collectives: RS over the inner (ICI) axis, AR of the shards over
    # the outer (DCN) axis, AG back — must equal the flat psum over
    # both axes (ragged length exercises the inner-pad path).
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.jax import spmd

    devs = np.asarray(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "ici"))
    x = jnp.asarray(np.random.RandomState(3).randn(8, 6), jnp.float32)

    def f(xs):
        v = xs.reshape(-1)  # [6], 6 % 4 != 0 -> pad path
        h_sum = spmd.hierarchical_allreduce(
            v, op="Sum", inner_axis="ici", outer_axis="dcn")
        # The DistributedOptimizer plumbing: a (inner, outer) pair
        # routes the pytree through the hierarchical reduce.
        from horovod_tpu.jax.optimizer import allreduce_gradients
        h_avg = allreduce_gradients(
            {"g": v}, op="Average", axis_name=("ici", "dcn"))["g"]
        flat = jax.lax.psum(v, ("dcn", "ici"))
        return h_sum[None], h_avg[None], flat[None]

    fn = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P(("dcn", "ici")),
        out_specs=(P(("dcn", "ici")), P(("dcn", "ici")),
                   P(("dcn", "ici"))), check_vma=False))
    h_sum, h_avg, flat = fn(x)
    np.testing.assert_allclose(np.asarray(h_sum), np.asarray(flat),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h_avg),
                               np.asarray(flat) / 8.0, rtol=1e-5)


def test_world_mesh_rejects_uneven_device_counts(monkeypatch):
    # Heterogeneous pods (e.g. a mixed slice after an elastic resize)
    # must fail mesh build with an actionable message, not a reshape
    # error deep in sharding code.
    import pytest as _pytest

    from horovod_tpu.jax import data_parallel as dp

    class FakeDev:
        def __init__(self, p, i):
            self.process_index, self.id = p, i

    monkeypatch.setattr(dp, "_multihost", lambda: True)
    monkeypatch.setattr(dp.jax, "devices",
                        lambda: [FakeDev(0, 0), FakeDev(0, 1),
                                 FakeDev(1, 2)])
    with _pytest.raises(Exception, match="EQUAL addressable-device"):
        dp._world_mesh()


def test_adapter_reexports_full_surface(hvd_world):
    for name in ("init", "rank", "size", "allreduce", "grouped_allreduce",
                 "allgather", "broadcast", "alltoall", "reducescatter",
                 "barrier", "join", "DistributedOptimizer",
                 "DistributedGradientTape", "Compression",
                 "broadcast_parameters", "broadcast_optimizer_state",
                 "broadcast_object", "SyncBatchNorm", "ProcessSet",
                 "add_process_set", "spmd"):
        assert hasattr(hvd, name), name
