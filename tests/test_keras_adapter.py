"""Keras adapter/callback tests.

Reference parity: the Keras callback coverage inside
``test/parallel/test_tensorflow2_keras.py`` — broadcast callback, metric
averaging, LR warmup and schedule.  Size-1 tcp world.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")


@pytest.fixture(scope="module")
def hvd():
    import horovod_tpu.keras as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


def _model(lr=0.1):
    m = keras.Sequential(
        [keras.layers.Dense(1, input_shape=(2,), use_bias=False)])
    m.compile(optimizer=keras.optimizers.SGD(lr), loss="mse")
    return m


def _fit(model, cbs, epochs=1, batches=4):
    x = np.ones((batches * 2, 2), np.float32)
    y = np.zeros((batches * 2, 1), np.float32)
    return model.fit(x, y, epochs=epochs, batch_size=2, verbose=0,
                     shuffle=False, callbacks=cbs)


def test_broadcast_callback(hvd):
    model = _model()
    cb = hvd.callbacks.BroadcastGlobalVariablesCallback(root_rank=0)
    _fit(model, [cb])
    assert cb.broadcast_done


def test_metric_average_callback(hvd):
    model = _model()
    cb = hvd.callbacks.MetricAverageCallback()
    hist = _fit(model, [cb])
    assert "loss" in hist.history


def test_lr_warmup(hvd):
    model = _model(lr=0.5)
    cb = hvd.callbacks.LearningRateWarmupCallback(
        initial_lr=0.5, warmup_epochs=2, steps_per_epoch=4)
    _fit(model, [cb], epochs=3)
    assert np.isclose(float(model.optimizer.learning_rate.numpy()), 0.5)


def test_lr_schedule(hvd):
    model = _model(lr=1.0)
    cb = hvd.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e, staircase=True)
    _fit(model, [cb], epochs=2)
    assert np.isclose(float(model.optimizer.learning_rate.numpy()), 0.1)


def test_load_model_rewraps_optimizer(hvd, tmp_path):
    model = _model()
    path = str(tmp_path / "m.keras")
    model.save(path)
    loaded = hvd.load_model(path)
    assert getattr(type(loaded.optimizer), "_hvd_distributed", False)


def test_momentum_correction_scales_velocity(hvd):
    model = _model(lr=1.0)
    model.compile(optimizer=keras.optimizers.SGD(1.0, momentum=0.9),
                  loss="mse")
    # Build the optimizer slots, then seed a known velocity.
    _fit(model, [], epochs=1)
    for v in model.optimizer.momentums:
        v.assign(keras.ops.ones_like(v) * 4.0)
    cb = hvd.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.5, staircase=True,
        momentum_correction=True)
    cb.set_model(model)
    cb.set_params({"steps": 4})
    cb.on_train_begin()
    cb.on_epoch_begin(1)
    # LR 1.0 -> 0.5: velocity scaled by 0.5 (4.0 -> 2.0).
    got = keras.ops.convert_to_numpy(model.optimizer.momentums[0])
    assert np.allclose(got, 2.0), got
    assert np.isclose(float(model.optimizer.learning_rate.numpy()), 0.5)
