"""Mesh construction helper tests (8-device CPU world)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.jax.mesh import create_hybrid_mesh, create_mesh


def test_create_mesh_shapes_and_collectives(hvd_world):
    mesh = create_mesh((2, 4), ("dp", "tp"))
    assert mesh.shape == {"dp": 2, "tp": 4}
    out = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
        in_specs=P(None, "tp"), out_specs=P(None, None),
        check_vma=False))(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(np.asarray(out), 4.0)


def test_create_mesh_validates_count(hvd_world):
    with pytest.raises(ValueError):
        create_mesh((3, 4), ("a", "b"))


def test_create_hybrid_mesh_fallback_layout(hvd_world):
    # 2 "slices" x 4 chips: dp crosses slices, mp stays inner
    mesh = create_hybrid_mesh((1, 4), (2, 1), ("dp", "mp"))
    assert mesh.shape == {"dp": 2, "mp": 4}
    # inner mp rows must be the contiguous per-slice device groups
    devs = np.asarray(jax.devices())
    arr = np.array(mesh.devices)
    assert set(d.id for d in arr[0]) == set(d.id for d in devs[:4])
    assert set(d.id for d in arr[1]) == set(d.id for d in devs[4:])


def test_create_hybrid_mesh_validates(hvd_world):
    with pytest.raises(ValueError):
        create_hybrid_mesh((1, 4), (2,), ("dp", "mp"))
    with pytest.raises(ValueError):
        create_hybrid_mesh((1, 2), (2, 1), ("dp", "mp"))
