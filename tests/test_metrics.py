"""Metrics & structured-events plane tests.

Registry units (thread safety, log2 histogram buckets, label
cardinality guard, runtime name strictness), Prometheus exposition
well-formedness, the JSONL journal round trip, instrumented-seam
assertions (faultline fire -> counter + journal, stall warning ->
counter, RPC retry counters, /metrics on the rendezvous server,
timeline valid-tail durability), and — slow-marked, run by the CI
fault-smoke job — a 2-proc multihost elastic world whose driver
``/metrics`` is scraped mid-run under fault injection (observability
certified under injection, the r7 pattern).
"""

import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.common import faultline, metrics
from tests.utils.spawn import scaled_timeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


# -- registry units --------------------------------------------------------

def test_counter_gauge_basics():
    metrics.counter("engine_cycles_total").inc()
    metrics.counter("engine_cycles_total").inc(4)
    assert metrics.counter("engine_cycles_total").value == 5
    metrics.gauge("elastic_epoch").set(7)
    metrics.gauge("elastic_epoch").set(3)
    assert metrics.gauge("elastic_epoch").value == 3
    # Label order must not fork a series.
    metrics.counter("mh_bus_bytes_total", op="allreduce", path="flat").inc(2)
    metrics.counter("mh_bus_bytes_total", path="flat", op="allreduce").inc(3)
    assert metrics.counter("mh_bus_bytes_total", op="allreduce",
                           path="flat").value == 5


def test_unregistered_and_kind_mismatch_raise():
    with pytest.raises(KeyError):
        metrics.counter("totally_made_up_series")
    with pytest.raises(ValueError):
        metrics.gauge("engine_cycles_total")  # declared as a counter
    with pytest.raises(ValueError):
        metrics.counter("elastic_epoch")      # declared as a gauge


def test_counter_thread_safety():
    n_threads, per_thread = 8, 500

    def worker():
        for _ in range(per_thread):
            metrics.counter("rpc_attempts_total").inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.counter("rpc_attempts_total").value == \
        n_threads * per_thread


def test_histogram_log2_buckets():
    h = metrics.histogram("engine_cycle_seconds")
    h.observe(0.0009)   # <= 2^-10
    h.observe(0.7)      # <= 2^0
    h.observe(3.0)      # <= 2^2
    h.observe(1e9)      # beyond the top finite bucket: +Inf only
    snap = metrics.snapshot()["engine_cycle_seconds"]["series"][0]
    assert snap["count"] == 4
    assert sum(snap["buckets"].values()) == 3  # 1e9 is +Inf-only
    assert abs(snap["sum"] - (0.0009 + 0.7 + 3.0 + 1e9)) < 1.0
    text = metrics.render_prometheus()
    # Cumulative bucket counts, le ascending, +Inf = total count.
    les = [(float(m.group(1)) if m.group(1) != "+Inf" else float("inf"),
            int(m.group(2)))
           for m in re.finditer(
               r'engine_cycle_seconds_bucket\{le="([^"]+)"\} (\d+)',
               text)]
    assert les == sorted(les), text
    counts = [c for _, c in les]
    assert counts == sorted(counts) and counts[-1] == 4, text


def test_label_cardinality_guard(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_MAX_SERIES", "4")
    for i in range(10):
        metrics.counter("fault_injections_total",
                        site="site%d" % i, action="drop").inc()
    fam = metrics.snapshot()["fault_injections_total"]["series"]
    # 4 real series + the overflow catch-all.
    assert len(fam) == 5
    overflow = [s for s in fam if s["labels"] == {"overflow": "true"}]
    assert overflow and overflow[0]["value"] == 6
    assert metrics.counter("metrics_dropped_series_total").value == 6


# -- Prometheus exposition -------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (\S+)$')


def assert_prometheus_wellformed(text: str):
    """Minimal exposition-format validator: HELP/TYPE comments only,
    one TYPE per family, parseable sample lines, histogram buckets
    carry le labels."""
    assert text.endswith("\n")
    typed = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*) (.+)$",
                         line)
            assert m, "bad comment line: %r" % line
            if m.group(1) == "TYPE":
                assert m.group(2) not in typed, \
                    "duplicate TYPE for %s" % m.group(2)
                assert m.group(3) in ("counter", "gauge", "histogram")
                typed[m.group(2)] = m.group(3)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, "bad sample line: %r" % line
        float(m.group(3))  # value parses
        base = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        assert base in typed or m.group(1) in typed, \
            "sample %r precedes its TYPE" % line
        if m.group(1).endswith("_bucket"):
            assert 'le="' in (m.group(2) or ""), line


def test_prometheus_render_well_formed():
    metrics.counter("engine_cycles_total").inc()
    metrics.gauge("engine_queue_depth").set(3)
    metrics.histogram("mh_collective_seconds", op="allreduce",
                      size_class="4096").observe(0.01)
    metrics.counter("events_total", kind="drain_request").inc()
    assert_prometheus_wellformed(metrics.render_prometheus())


def test_render_merged_adds_rank_label():
    metrics.counter("engine_cycles_total").inc(2)
    snap = metrics.snapshot()
    text = metrics.render_merged([("driver", snap), ("1", snap)])
    assert_prometheus_wellformed(text)
    assert 'engine_cycles_total{rank="driver"} 2' in text
    assert 'engine_cycles_total{rank="1"} 2' in text
    assert text.count("# TYPE engine_cycles_total counter") == 1


# -- journal ---------------------------------------------------------------

def test_journal_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_RANK", "2")
    metrics.event("stall", tensor="t1", age_secs=1.5)
    metrics.event("drain_request", reason="test")
    metrics.event("election", root_rank=0)
    records = list(metrics.iter_events())
    assert [r["seq"] for r in records] == [1, 2, 3]
    assert all(r["rank"] == 2 for r in records)
    assert [r["kind"] for r in records] == \
        ["stall", "drain_request", "election"]
    assert records[0]["tensor"] == "t1"
    # Rank-stamped filename; one file per writer.
    assert os.listdir(str(tmp_path)) == ["events-r2.jsonl"]
    # Events mirror into the counter whether or not the journal is on.
    assert metrics.counter("events_total", kind="stall").value == 1


def test_render_merged_keeps_series_own_rank_label():
    # The skew observatory's straggler_score is keyed by the SCORED
    # rank; the fleet merge's source label must not clobber it (it
    # would collapse every score into duplicate {rank="driver"}
    # series — invalid exposition).  Labels the series does NOT carry
    # still gain the source tag.
    metrics.gauge("straggler_score", rank="0").set(0.5)
    metrics.gauge("straggler_score", rank="1").set(12.0)
    metrics.counter("elastic_spawn_total").inc()
    text = metrics.render_merged([("driver", metrics.snapshot())])
    assert 'straggler_score{rank="0"} 0.5' in text
    assert 'straggler_score{rank="1"} 12' in text
    assert 'rank="driver"' not in \
        [l for l in text.splitlines()
         if l.startswith("straggler_score")][0]
    assert 'elastic_spawn_total{rank="driver"} 1' in text


def test_iter_events_merged_across_writers(tmp_path):
    # ISSUE 12 satellite: the merged reader interleaves ALL writers by
    # (ts, writer, seq) and stamps each record with its writer tag, so
    # cross-rank correlation needs no per-file stitching.  Two writers
    # with interleaved timestamps, including a same-ts tie broken by
    # writer then seq.
    def write(writer, records):
        with open(os.path.join(str(tmp_path),
                               "events-%s.jsonl" % writer), "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")

    write("driver", [
        {"ts": 1.0, "seq": 1, "kind": "epoch_published"},
        {"ts": 3.0, "seq": 2, "kind": "drained"},
        {"ts": 5.0, "seq": 3, "kind": "straggler_detected"},
    ])
    write("r1", [
        {"ts": 2.0, "seq": 1, "kind": "spawn_seen"},
        {"ts": 3.0, "seq": 2, "kind": "drain_request"},
        {"ts": 4.0, "seq": 3, "kind": "fault_fire"},
    ])
    merged = list(metrics.iter_events(str(tmp_path), merged=True))
    assert [(r["ts"], r["writer"], r["seq"]) for r in merged] == [
        (1.0, "driver", 1), (2.0, "r1", 1), (3.0, "driver", 2),
        (3.0, "r1", 2), (4.0, "r1", 3), (5.0, "driver", 3)]
    assert [r["kind"] for r in merged] == [
        "epoch_published", "spawn_seen", "drained", "drain_request",
        "fault_fire", "straggler_detected"]
    # Default (unmerged) behavior is unchanged: file order, no writer
    # stamp.
    flat = list(metrics.iter_events(str(tmp_path)))
    assert [r["kind"] for r in flat[:3]] == [
        "epoch_published", "drained", "straggler_detected"]
    assert "writer" not in flat[0]


def test_approx_quantile_log2_estimator():
    # 100 fast observations and 10 slow ones: the shared estimator
    # must put p50 inside the fast bucket, p99 near its top, and the
    # extreme tail inside the slow bucket — within the log2 bucket
    # geometry's 2x bound, labels filtered by subset match.
    h = metrics.histogram("mh_collective_seconds", op="allreduce",
                          size_class="65536")
    for _ in range(100):
        h.observe(0.01)
    for _ in range(10):
        h.observe(1.0)
    other = metrics.histogram("mh_collective_seconds", op="allgather",
                              size_class="1024")
    other.observe(100.0)  # wrong labels: must not pollute
    snap = metrics.snapshot()
    p50 = metrics.approx_quantile(snap, "mh_collective_seconds", 0.50,
                                  {"op": "allreduce"})
    assert 0.0078125 <= p50 <= 0.015625, p50  # 0.01's bucket
    tail = metrics.approx_quantile(snap, "mh_collective_seconds",
                                   0.999, {"op": "allreduce"})
    assert 0.5 <= tail <= 1.024, tail  # 1.0's bucket
    # Aggregation across series (no label filter) covers both ops.
    assert metrics.approx_quantile(snap, "mh_collective_seconds",
                                   1.0) >= 64.0
    # Absent family / empty labels-match degrade to 0.
    assert metrics.approx_quantile(snap, "nope", 0.5) == 0.0
    assert metrics.approx_quantile(
        snap, "mh_collective_seconds", 0.5, {"op": "bcast"}) == 0.0
    # Beyond-top-bucket overflow clamps to the top finite edge.
    big = metrics.histogram("engine_cycle_seconds")
    big.observe(1000.0)
    assert metrics.approx_quantile(metrics.snapshot(),
                                   "engine_cycle_seconds", 1.0) == 64.0


def test_journal_disabled_without_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_METRICS_DIR", raising=False)
    metrics.event("stall", tensor="x")
    assert metrics.counter("events_total", kind="stall").value == 1
    assert list(metrics.iter_events(str(tmp_path))) == []


# -- instrumented seams ----------------------------------------------------

def test_faultline_fire_increments_counter_and_journal(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_FAULT", "engine.cycle.pre:delay:0.0")
    faultline.reset()
    try:
        assert faultline.site("engine.cycle.pre") is False
        assert metrics.counter("fault_injections_total",
                               site="engine.cycle.pre",
                               action="delay").value == 1
        fires = [r for r in metrics.iter_events()
                 if r["kind"] == "fault_fire"]
        assert len(fires) == 1
        assert fires[0]["site"] == "engine.cycle.pre"
        assert fires[0]["action"] == "delay"
    finally:
        faultline.reset()


def test_stall_warning_counts_and_journals(tmp_path, monkeypatch):
    from horovod_tpu.utils.stall_inspector import StallInspector
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    si = StallInspector(warning_secs=0.05, reporter=lambda msg: None)
    si.record_enqueue("grad_7", missing_ranks=[1, 3])
    time.sleep(0.12)
    assert si.check() == ["grad_7"]
    assert metrics.counter("stall_detected_total").value == 1
    stalls = [r for r in metrics.iter_events() if r["kind"] == "stall"]
    assert stalls and stalls[0]["tensor"] == "grad_7"
    assert stalls[0]["missing_ranks"] == [1, 3]


def test_rpc_retry_counters():
    from horovod_tpu.runner.http_client import request_with_retry
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("flake")
        return 42

    assert request_with_retry(flaky, what="test", max_retries=5,
                              backoff=0.001, deadline=5.0) == 42
    assert metrics.counter("rpc_attempts_total").value == 3
    assert metrics.counter("rpc_transient_failures_total").value == 2
    assert metrics.counter("rpc_giveups_total").value == 0

    def always_down():
        raise ConnectionResetError("down")

    with pytest.raises(ConnectionResetError):
        request_with_retry(always_down, what="test", max_retries=1,
                           backoff=0.001, deadline=5.0)
    assert metrics.counter("rpc_giveups_total").value == 1


def test_http_server_metrics_endpoint_unauthenticated():
    from horovod_tpu.runner.http_server import RendezvousServer
    metrics.counter("engine_cycles_total").inc(9)
    server = RendezvousServer(host="127.0.0.1", secret="sekrit")
    port = server.start()
    try:
        url = "http://127.0.0.1:%d/metrics" % port
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "engine_cycles_total 9" in text
        assert_prometheus_wellformed(text)
        # The KV paths stay HMAC-authenticated: no free rides.
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                "http://127.0.0.1:%d/some/key" % port, timeout=10)
        assert err.value.code == 403
    finally:
        server.stop()


def test_http_server_metrics_provider_override():
    from horovod_tpu.runner.http_server import RendezvousServer
    server = RendezvousServer(host="127.0.0.1", secret="s")
    server.metrics_provider = lambda: "# HELP x y\n# TYPE x counter\nx 1\n"
    port = server.start()
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port, timeout=10) as resp:
            assert resp.read().decode().endswith("x 1\n")
    finally:
        server.stop()


# -- timeline durability ---------------------------------------------------

def test_timeline_tail_stays_loadable_and_stop_is_tolerant(
        tmp_path, monkeypatch):
    from horovod_tpu.utils.timeline import Timeline
    monkeypatch.setenv("HOROVOD_TIMELINE_FLUSH_SECS", "0")
    path = str(tmp_path / "trace.json")
    tl = Timeline()
    tl.initialize(path)
    for i in range(3):
        tl.activity_start("t%d" % i, "EXEC_ALLREDUCE",
                          args={"group": i + 1})
        # With a zero cadence the on-disk array is valid after EVERY
        # record — the preempted-worker guarantee, observable.
        with open(path) as f:
            records = json.load(f)
        assert len(records) == i + 1
        assert records[i]["args"]["group"] == i + 1
    tl.shutdown()
    tl.shutdown()  # idempotent
    with open(path) as f:
        assert len(json.load(f)) == 3

    # Abort path: the file handle dies under the writer (drain force
    # exit, disk error) — emits and stop must not raise.
    tl2 = Timeline()
    tl2.initialize(str(tmp_path / "trace2.json"))
    tl2.activity_start("a", "X")
    tl2._fh.close()
    tl2.activity_start("b", "Y")   # swallowed, writer disabled
    tl2.shutdown()                 # tolerated after the abort
    with open(str(tmp_path / "trace2.json")) as f:
        assert json.load(f)[0]["name"] == "X"


def test_timeline_cadence_batches_tail_writes(tmp_path, monkeypatch):
    from horovod_tpu.utils.timeline import Timeline
    monkeypatch.setenv("HOROVOD_TIMELINE_FLUSH_SECS", "3600")
    path = str(tmp_path / "trace.json")
    tl = Timeline()
    tl.initialize(path)
    tl.activity_start("t", "X")   # first record: tail written (t=0 tick)
    tl.activity_start("u", "Y")   # inside the cadence window: no tail
    with open(path) as f:
        content = f.read()
    assert not content.rstrip().endswith("]")
    tl.shutdown()
    with open(path) as f:
        assert len(json.load(f)) == 2


# -- in-process engine integration ----------------------------------------

def test_engine_series_from_inprocess_world():
    import jax
    import numpy as np
    import horovod_tpu as hvd
    hvd.init(devices=jax.devices()[:1])
    try:
        out = hvd.allreduce(np.ones((1, 16), np.float32), op=hvd.Sum,
                            name="metrics_probe")
        assert float(np.asarray(out).reshape(-1)[0]) == 1.0
        snap = hvd.metrics_snapshot()
        assert snap["engine_cycles_total"]["series"][0]["value"] >= 1
        assert snap["engine_bytes_submitted_total"]["series"][0][
            "value"] >= 16 * 4
        assert snap["engine_last_group_id"]["series"][0]["value"] >= 1
        assert "exec_cache_misses" in snap
    finally:
        hvd.shutdown()


# -- e2e: fleet-wide scrape under injection (CI fault-smoke) ---------------

E2E_WORKER = """
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0)

@elastic.run
def train(state):
    while not os.path.exists(%(stop)r) or state.batch < 4:
        out = hvd.allreduce(np.ones(64, np.float32), op=hvd.Sum,
                            name="b%%d" %% state.batch)
        assert float(np.asarray(out).reshape(-1)[0]) == float(hvd.size())
        state.batch += 1
        time.sleep(0.05)
        state.commit()
    print("DONE rank=%%d size=%%d batch=%%d"
          %% (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
"""


@pytest.mark.slow
def test_metrics_e2e_scrape_2proc(tmp_path, monkeypatch):
    """ISSUE 6 acceptance: curl the driver's /metrics mid-run on a live
    2-proc multihost elastic world — well-formed Prometheus text with
    engine cycle/fusion series, per-collective latency histograms and
    elastic event counters, all rank-labeled; an injected
    HVD_TPU_FAULT drop shows up as BOTH a counter increment in the
    scrape and a fault_fire line in the JSONL journal (observability
    certified under injection)."""
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver

    events_dir = tmp_path / "events"
    stop_file = tmp_path / "stop"
    script = tmp_path / "train.py"
    script.write_text(E2E_WORKER % {"stop": str(stop_file)})

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_ELASTIC_DRIVER_ADDR", None)
    env["HOROVOD_CONTROLLER"] = "multihost"
    env["HOROVOD_METRICS_DIR"] = str(events_dir)
    # Fires once per worker at the first rendezvous poll: a bounded,
    # recoverable drop whose only lasting trace is observability.
    env["HVD_TPU_FAULT"] = "elastic.rendezvous.poll:drop@times=1"
    # The driver journals into the same dir (it runs in this process).
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(events_dir))

    driver = ElasticDriver(
        [sys.executable, str(script)],
        FixedHosts({"127.0.0.1": 1, "127.0.0.2": 1}),
        min_np=2, max_np=2, env=env)
    result = {}
    t = threading.Thread(
        target=lambda: result.setdefault("rc", driver.run()),
        daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/metrics" % driver._kv.port
    deadline = time.monotonic() + scaled_timeout(300)
    text = ""
    try:
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=5) as resp:
                    text = resp.read().decode()
            except Exception:
                time.sleep(1.0)
                continue
            if ("engine_cycles_total{" in text
                    and "mh_collective_seconds_bucket" in text
                    and "fault_injections_total" in text):
                break
            time.sleep(1.0)
    finally:
        stop_file.write_text("")  # let the workers finish either way
    t.join(scaled_timeout(300))
    assert not t.is_alive(), "driver never finished"
    assert result.get("rc") == 0

    # The mid-run scrape carried every plane, rank-labeled.
    assert "mh_collective_seconds_bucket" in text, text[-2000:]
    assert_prometheus_wellformed(text)
    assert re.search(r'engine_cycles_total\{rank="[01]"\}', text), text
    assert "engine_bytes_submitted_total" in text
    assert re.search(r'mh_collective_seconds_bucket\{[^}]*le="[^"]+"'
                     r'[^}]*op="allreduce"', text), text
    assert re.search(r'mh_collective_path_total\{[^}]*rank="[01]"', text)
    m = re.search(r'elastic_spawn_total\{rank="driver"\} (\d+)', text)
    assert m and int(m.group(1)) >= 2, text
    assert 'elastic_epoch{rank="driver"}' in text
    # Injected drop: counter increment in the scrape ...
    assert re.search(
        r'fault_injections_total\{[^}]*site="elastic\.rendezvous\.poll"'
        r'[^}]*\} 1', text), text
    # ... and a journal event on disk (one per worker process;
    # @times=1 bounds it per process, a respawn may add one more).
    records = list(metrics.iter_events(str(events_dir)))
    fires = [r for r in records if r["kind"] == "fault_fire"]
    assert len(fires) >= 2, records
    assert all(r["site"] == "elastic.rendezvous.poll" for r in fires)
    # Driver-side lifecycle events journaled too, rank-stamped schema.
    kinds = {r["kind"] for r in records}
    assert "spawn" in kinds and "epoch_published" in kinds
    assert all("seq" in r and "ts" in r for r in records)
