"""Expert-parallel MoE and pipeline parallelism tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.moe import MoeConfig, init_moe_params, moe_ffn
from horovod_tpu.parallel.pipeline import pipeline_apply, split_microbatches


def test_moe_local_vs_expert_parallel(hvd_world):
    """Same experts, ep=1 vs ep=8: outputs must match."""
    cfg = MoeConfig(n_experts=8, d_model=16, d_ff=32, top_k=2,
                    capacity_factor=8.0)  # capacity high: no drops
    key = jax.random.PRNGKey(0)
    full = init_moe_params(key, cfg, experts_per_shard=8)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8 * 4, 16).astype(np.float32))

    y_local, aux_local = moe_ffn(full, x, cfg, axis_name=None)

    mesh = Mesh(np.asarray(jax.devices()), ("ep",))
    # Shard experts across ep; tokens across ep too; router replicated.
    shard_params = {
        "router": full["router"],
        "w1": full["w1"], "w3": full["w3"], "w2": full["w2"],
    }
    f = jax.jit(jax.shard_map(
        lambda p, t: moe_ffn(p, t, cfg, axis_name="ep"),
        mesh=mesh,
        in_specs=({"router": P(), "w1": P("ep"), "w3": P("ep"),
                   "w2": P("ep")}, P("ep")),
        out_specs=(P("ep"), P()), check_vma=False))
    y_ep, aux_ep = f(shard_params, x)
    # Note: token sharding changes per-shard capacity accounting; with a
    # generous capacity factor both paths route every token.
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_local),
                               rtol=2e-3, atol=2e-3)


def test_moe_capacity_drops_tokens(hvd_world):
    cfg = MoeConfig(n_experts=4, d_model=8, d_ff=16, top_k=1,
                    capacity_factor=0.25)  # tight capacity -> drops
    params = init_moe_params(jax.random.PRNGKey(1), cfg, experts_per_shard=4)
    x = jnp.asarray(np.random.RandomState(1).randn(32, 8).astype(np.float32))
    y, aux = moe_ffn(params, x, cfg, axis_name=None)
    # Dropped tokens produce zero output rows; some must survive.
    norms = np.linalg.norm(np.asarray(y), axis=1)
    assert (norms > 1e-6).any()
    assert float(aux) > 0


def test_moe_gradients_flow(hvd_world):
    cfg = MoeConfig(n_experts=4, d_model=8, d_ff=16, top_k=2,
                    capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(2), cfg, experts_per_shard=4)
    x = jnp.asarray(np.random.RandomState(2).randn(16, 8).astype(np.float32))

    def loss(p):
        y, aux = moe_ffn(p, x, cfg, axis_name=None)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
    assert float(jnp.abs(g["router"]).sum()) > 0


def test_pipeline_matches_sequential(hvd_world):
    """8-stage pipeline == running all layers sequentially."""
    n_layers, d = 8, 6
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(n_layers, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(16, d).astype(np.float32))

    def layer(w, h):
        return jnp.tanh(h @ w)

    # Sequential reference.
    ref = x
    for i in range(n_layers):
        ref = layer(ws[i], ref)

    mesh = Mesh(np.asarray(jax.devices()), ("pp",))

    def stage_fn(stage_ws, h):
        # One layer per stage here (8 stages x 1 layer).
        return layer(stage_ws[0], h)

    mbs = split_microbatches(x, 4)
    f = jax.jit(jax.shard_map(
        lambda w, m: pipeline_apply(w, m, stage_fn, axis_name="pp"),
        mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))
    out = f(ws, mbs)
    got = out.reshape(16, d)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_gradients_match_sequential(hvd_world):
    n_layers, d = 8, 4
    rng = np.random.RandomState(1)
    ws = jnp.asarray(rng.randn(n_layers, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(8, d).astype(np.float32))

    def layer(w, h):
        return jnp.tanh(h @ w)

    def seq_loss(ws):
        h = x
        for i in range(n_layers):
            h = layer(ws[i], h)
        return jnp.mean(h ** 2)

    mesh = Mesh(np.asarray(jax.devices()), ("pp",))

    def pp_loss(ws):
        def inner(w, m):
            out = pipeline_apply(
                w, m, lambda sw, h: layer(sw[0], h), axis_name="pp")
            return jnp.mean(out ** 2)
        f = jax.shard_map(inner, mesh=mesh, in_specs=(P("pp"), P()),
                          out_specs=P(), check_vma=False)
        return f(ws, split_microbatches(x, 2))

    g_ref = jax.grad(seq_loss)(ws)
    g_pp = jax.jit(jax.grad(pp_loss))(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)
