"""Multihost-mode tests: N real processes × forced CPU devices joined in
ONE global JAX runtime.  The native core negotiates (control plane), the
multihost engine executes XLA collectives over the global mesh (payload
plane) — the reference's MPI-control/NCCL-payload split re-based on
``jax.distributed`` (SURVEY.md §2.6)."""

import os

import pytest

from tests.utils.spawn import assert_world_ok, spawn_world

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "utils",
                      "multihost_worker.py")


def _spawn_multihost(size, local_devices=4, extra_env=None, timeout=240,
                     worker=WORKER):
    env = {"HOROVOD_CONTROLLER": "multihost",
           "TEST_LOCAL_DEVICES": str(local_devices)}
    env.update(extra_env or {})
    # base+size+101 is the derived jax coordinator port
    # (common/multihost.py); probe it free along with the tcp-core range.
    return spawn_world(worker, size, extra_env=env, timeout=timeout,
                       extra_port_offsets=(size + 101,),
                       pop_env=("XLA_FLAGS",))


def _assert_ok(outs, marker="MULTIHOST_OK"):
    assert_world_ok(outs, marker)


@pytest.mark.parametrize("size", [2, 3])
def test_multihost_collective_matrix(size, tmp_path):
    # Full eager matrix over a real multi-process global mesh: fused and
    # grouped allreduce, every reduce op, ragged allgather/alltoall,
    # uneven reducescatter, process sets, join with zero contribution.
    # HVD_TPU_DUMP_HLO makes the worker also assert device payloads stay
    # device-resident and the programs lower to real collective HLO
    # (all_reduce / all_to_all / reduce_scatter).
    # TEST_TIMELINE_BASE additionally makes each worker assert its
    # chrome trace contains the executor's device-exec spans.
    _assert_ok(_spawn_multihost(size, extra_env={
        "HVD_TPU_DUMP_HLO": "1",
        "TEST_TIMELINE_BASE": str(tmp_path / "tl")}))


def test_multihost_single_local_device():
    # One device per process: the degenerate pod-of-single-chip-hosts
    # layout must behave identically.
    _assert_ok(_spawn_multihost(2, local_devices=1))


DP_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "utils", "multihost_dp_worker.py")


def test_multihost_data_parallel_step_matches_reference():
    # make_data_parallel_step over 2 processes x 2 devices: the update
    # must equal the single-process full-batch SGD step exactly (the
    # gradients are the global-batch mean by construction).
    _assert_ok(_spawn_multihost(2, local_devices=2, worker=DP_WORKER),
               marker="MH_DP_OK")


WATCHDOG_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "utils", "multihost_watchdog_worker.py")


def test_execution_watchdog_fails_survivors_loudly():
    # VERDICT r3 item 4: a member that wedges BETWEEN negotiation and
    # dispatch (alive, but never joining the compiled program — the
    # undetectable-on-ICI failure) blocks survivors inside the runtime
    # where the negotiation-phase stall inspector cannot see them.
    # Rank 1 negotiates the marked group but never dispatches; rank
    # 0's watchdog (HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS=6) must fail
    # the handle with a diagnostic naming the group, reject new work,
    # and let the process exit cleanly — all well inside the 60 s wait.
    outs = _spawn_multihost(2, local_devices=2, extra_env={
        "HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS": "6",
    }, worker=WATCHDOG_WORKER)
    rc0, out0, err0 = outs[0]
    rc1, out1, _err1 = outs[1]
    assert rc0 == 0, "survivor rank 0 failed (rc=%d):\n%s\n%s" % (
        rc0, out0, err0)
    assert "MH_WATCHDOG_OK 0" in out0, out0
    # Rank 1 wedged by design and dies when the coordination service
    # notices rank 0's exit — its exact exit code is runtime noise,
    # but it must never report success.
    assert rc1 != 0 and "MH_WATCHDOG_OK" not in out1, (rc1, out1)


def test_init_detects_preinitialized_runtime(monkeypatch):
    # A pre-initialized JAX backend makes jax.distributed.initialize a
    # silent no-op: every rank would train alone while believing it is
    # rank r of N.  init_jax_distributed must detect the world that
    # failed to form and raise, not proceed.
    import types

    from horovod_tpu.common import multihost as mh

    fake_jax = types.SimpleNamespace(
        config=types.SimpleNamespace(
            update=lambda *a, **k: None, jax_platforms="cpu"),
        distributed=types.SimpleNamespace(
            initialize=lambda **kw: None),  # the silent no-op
        process_count=lambda: 1,            # world never formed
    )
    monkeypatch.setattr(mh, "init_jax_distributed",
                        mh.init_jax_distributed)
    monkeypatch.setitem(__import__("sys").modules, "jax", fake_jax)
    monkeypatch.setattr(mh.init_jax_distributed, "_done", False,
                        raising=False)
    cfg = types.SimpleNamespace(coordinator_addr="127.0.0.1:1",
                                rendezvous_addr=None, secret_key=None)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="train alone|initialized "
                                            "before|process_count"):
        mh.init_jax_distributed(cfg, rank=0, size=2)
    mh.init_jax_distributed._done = False
