"""Multihost-mode tests: N real processes × forced CPU devices joined in
ONE global JAX runtime.  The native core negotiates (control plane), the
multihost engine executes XLA collectives over the global mesh (payload
plane) — the reference's MPI-control/NCCL-payload split re-based on
``jax.distributed`` (SURVEY.md §2.6)."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "utils",
                      "multihost_worker.py")

_port_base = [31700]


def _free_block(size):
    """A port base whose tcp-core range [base, base+size) AND the derived
    jax coordinator port (base+size+101) are currently bindable.  Earlier
    suite tests spawn and kill real worker processes; a lingering socket
    on a deterministically-derived port hangs the rendezvous instead of
    failing fast, so probe before committing to a base."""
    for _ in range(200):
        _port_base[0] += size + 120
        base = _port_base[0]
        socks = []
        try:
            for port in list(range(base, base + size)) + [base + size + 101]:
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", port))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port block found")


def _spawn_multihost(size, local_devices=4, extra_env=None, timeout=240,
                     worker=WORKER, _retry=True):
    base = _free_block(size)
    procs = []
    for rank in range(size):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(size),
            "HOROVOD_PORT_BASE": str(base),
            "HOROVOD_CONTROLLER": "multihost",
            "TEST_LOCAL_DEVICES": str(local_devices),
            "HOROVOD_CYCLE_TIME": "1",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            for q in procs:
                try:
                    q.communicate(timeout=10)
                except Exception:  # noqa: BLE001 - best-effort reap
                    pass
            if _retry:
                # One retry on a fresh port block: multi-process rendezvous
                # can wedge on transient socket conditions under suite load.
                return _spawn_multihost(size, local_devices, extra_env,
                                        timeout, worker, _retry=False)
            raise
        outs.append((p.returncode, out.decode(), err.decode()))
    return outs


def _assert_ok(outs, marker="MULTIHOST_OK"):
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, "rank %d failed (rc=%d):\n%s\n%s" % (rank, rc,
                                                             out, err)
        assert "%s %d" % (marker, rank) in out, out


@pytest.mark.parametrize("size", [2, 3])
def test_multihost_collective_matrix(size):
    # Full eager matrix over a real multi-process global mesh: fused and
    # grouped allreduce, every reduce op, ragged allgather/alltoall,
    # uneven reducescatter, process sets, join with zero contribution.
    _assert_ok(_spawn_multihost(size))


def test_multihost_single_local_device():
    # One device per process: the degenerate pod-of-single-chip-hosts
    # layout must behave identically.
    _assert_ok(_spawn_multihost(2, local_devices=1))


DP_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "utils", "multihost_dp_worker.py")


def test_multihost_data_parallel_step_matches_reference():
    # make_data_parallel_step over 2 processes x 2 devices: the update
    # must equal the single-process full-batch SGD step exactly (the
    # gradients are the global-batch mean by construction).
    _assert_ok(_spawn_multihost(2, local_devices=2, worker=DP_WORKER),
               marker="MH_DP_OK")
