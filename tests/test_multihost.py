"""Multihost-mode tests: N real processes × forced CPU devices joined in
ONE global JAX runtime.  The native core negotiates (control plane), the
multihost engine executes XLA collectives over the global mesh (payload
plane) — the reference's MPI-control/NCCL-payload split re-based on
``jax.distributed`` (SURVEY.md §2.6)."""

import os

import pytest

from tests.utils.spawn import assert_world_ok, spawn_world

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "utils",
                      "multihost_worker.py")


def _spawn_multihost(size, local_devices=4, extra_env=None, timeout=240,
                     worker=WORKER):
    env = {"HOROVOD_CONTROLLER": "multihost",
           "TEST_LOCAL_DEVICES": str(local_devices)}
    env.update(extra_env or {})
    # base+size+101 is the derived jax coordinator port
    # (common/multihost.py); probe it free along with the tcp-core range.
    return spawn_world(worker, size, extra_env=env, timeout=timeout,
                       extra_port_offsets=(size + 101,),
                       pop_env=("XLA_FLAGS",))


def _assert_ok(outs, marker="MULTIHOST_OK"):
    assert_world_ok(outs, marker)


@pytest.mark.parametrize("size", [2, 3])
def test_multihost_collective_matrix(size, tmp_path):
    # Full eager matrix over a real multi-process global mesh: fused and
    # grouped allreduce, every reduce op, ragged allgather/alltoall,
    # uneven reducescatter, process sets, join with zero contribution.
    # HVD_TPU_DUMP_HLO makes the worker also assert device payloads stay
    # device-resident and the programs lower to real collective HLO
    # (all_reduce / all_to_all / reduce_scatter).
    # TEST_TIMELINE_BASE additionally makes each worker assert its
    # chrome trace contains the executor's device-exec spans.
    # The r9 hier-op sections (all five eager collectives on the
    # proc x local plane) run on the 2-proc world only: the 3-proc
    # world re-covers nothing (same multi-proc x multi-local shape)
    # at ~3x the compile+gloo cost on this 1-core box, and the suite
    # must stay inside the tier-1 budget.
    _assert_ok(_spawn_multihost(size, extra_env={
        "HVD_TPU_DUMP_HLO": "1",
        "TEST_HIER_OPS": "1" if size == 2 else "0",
        "TEST_TIMELINE_BASE": str(tmp_path / "tl")}))


def test_multihost_single_local_device():
    # One device per process: the degenerate pod-of-single-chip-hosts
    # layout must behave identically.  The r9 hier-op sections are
    # skipped: the hier plane never engages at k=1, so the big
    # payloads would re-time the one-device plane for no coverage.
    _assert_ok(_spawn_multihost(2, local_devices=1,
                                extra_env={"TEST_HIER_OPS": "0"}))


COMPRESSION_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "utils",
    "multihost_compression_worker.py")


@pytest.mark.slow
def test_multihost_cross_host_compression_int8():
    # ISSUE 7 acceptance: with HOROVOD_CROSS_HOST_COMPRESSION=int8 the
    # hier legs of all five eager collectives put int8 (+ per-chunk f32
    # scales) on the cross-host wire — numerics inside the quantization
    # error bounds, error feedback canceling the error across repeated
    # steps, and mh_bus_bytes_total / mh_compression_ratio asserting a
    # >= 3.5x wire-byte reduction vs the uncompressed payload IN the
    # worker (not just printed).  Sub-threshold payloads stay flat,
    # uncompressed and bit-exact.  slow-marked per the r9/r10 gating
    # pattern (CI perf-smoke runs it by node id); the 2-proc x 4-local
    # world is the cheapest shape that exercises a real proc x local
    # mesh.
    _assert_ok(_spawn_multihost(2, extra_env={
        "HOROVOD_CROSS_HOST_COMPRESSION": "int8",
        "HVD_TPU_DUMP_HLO": "1",
    }, worker=COMPRESSION_WORKER), marker="MH_COMPRESSION_OK")


DP_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "utils", "multihost_dp_worker.py")


def test_multihost_data_parallel_step_matches_reference():
    # make_data_parallel_step over 2 processes x 2 devices: the update
    # must equal the single-process full-batch SGD step exactly (the
    # gradients are the global-batch mean by construction).
    _assert_ok(_spawn_multihost(2, local_devices=2, worker=DP_WORKER),
               marker="MH_DP_OK")


WATCHDOG_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "utils", "multihost_watchdog_worker.py")


def test_execution_watchdog_fails_survivors_loudly():
    # VERDICT r3 item 4: a member that wedges BETWEEN negotiation and
    # dispatch (alive, but never joining the compiled program — the
    # undetectable-on-ICI failure) blocks survivors inside the runtime
    # where the negotiation-phase stall inspector cannot see them.
    # Rank 1 negotiates the marked group but never dispatches; rank
    # 0's watchdog (HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS=6) must fail
    # the handle with a diagnostic naming the group, reject new work,
    # and let the process exit cleanly — all well inside the 60 s wait.
    outs = _spawn_multihost(2, local_devices=2, extra_env={
        "HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS": "6",
    }, worker=WATCHDOG_WORKER)
    rc0, out0, err0 = outs[0]
    rc1, out1, _err1 = outs[1]
    assert rc0 == 0, "survivor rank 0 failed (rc=%d):\n%s\n%s" % (
        rc0, out0, err0)
    assert "MH_WATCHDOG_OK 0" in out0, out0
    # Rank 1 wedged by design and dies when the coordination service
    # notices rank 0's exit — its exact exit code is runtime noise,
    # but it must never report success.
    assert rc1 != 0 and "MH_WATCHDOG_OK" not in out1, (rc1, out1)


SHUTDOWN_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "utils", "multihost_shutdown_worker.py")


@pytest.mark.parametrize("ordering", ["rank0_exits_first",
                                      "rank0_exits_last"])
def test_multihost_shutdown_ordering(ordering):
    # ISSUE 2 acceptance: hvd.init -> collective -> hvd.shutdown with
    # BOTH exit orderings is rc=0 on all ranks.  The synchronized
    # teardown barrier makes the ordering irrelevant: no rank starts
    # jax.distributed.shutdown() until every rank reached the barrier,
    # and a process exiting early can no longer FATAL a peer still
    # inside teardown (the r6 MULTICHIP RED).  Exit skew is 2 s —
    # far beyond the window the coordination service needs to notice a
    # missing peer.
    late = "1" if ordering == "rank0_exits_first" else "0"
    outs = _spawn_multihost(2, local_devices=2, extra_env={
        "TEST_EXIT_DELAY_RANK%s" % late: "2.0",
    }, worker=SHUTDOWN_WORKER)
    _assert_ok(outs, marker="MH_SHUTDOWN_OK")


def test_multihost_shutdown_skewed_arrival():
    # One rank reaches teardown 1.5 s late (injected at the pre-barrier
    # fault site): the punctual rank must WAIT at the barrier, not run
    # ahead into jax.distributed.shutdown() and exit under its peer.
    outs = _spawn_multihost(2, local_devices=2, extra_env={
        "HVD_TPU_FAULT": "hvd.shutdown.pre_barrier:delay:1.5@rank=0",
    }, worker=SHUTDOWN_WORKER)
    _assert_ok(outs, marker="MH_SHUTDOWN_OK")


FAULT_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "utils", "multihost_fault_worker.py")


def test_enqueue_legacy_order_fails_loudly_not_wrong():
    # The once-intermittent control-plane race, now deterministic:
    # core.enqueue.legacy_order reverses rank 1's enqueue to the
    # pre-fix ordering (Request visible to the controller BEFORE the
    # handle is parked) and holds the vulnerability window open 3 s.
    # Negotiation completes inside the window, so rank 1's negotiated
    # record names an unparked entry.  Pre-PR that zero-filled the
    # reduction (silent corruption, tests/README.md's "known
    # intermittent"); now the core refuses: the record carries an
    # error, the engine poisons itself, and EVERY rank either verifies
    # the correct sum or raises HorovodInternalError.  rank 0's side is
    # covered by the execution watchdog (it dispatched a program rank 1
    # never joins).  The 3 s window dwarfs any plausible negotiation
    # latency (the background loop is a C++ thread, not GIL-bound;
    # a 2-rank negotiation is one localhost round-trip), so the race
    # fires deterministically even on a loaded 1-core box.
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "core.enqueue.legacy_order:delay:3.0@rank=1",
        "HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS": "6",
    }, worker=FAULT_WORKER)
    for rank, (rc, out, err) in enumerate(outs):
        assert rc in (0, 3), \
            "rank %d neither correct nor loud (rc=%d):\n%s\n%s" % (
                rank, rc, out, err)
        if rc == 0:
            assert "FAULT_OK %d" % rank in out, out
        else:
            assert "FAULT_LOUD %d" % rank in out, out
    # The injected rank itself must have failed loudly, not silently.
    assert outs[1][0] == 3, outs[1][1] + outs[1][2]
    assert "refusing to zero-fill" in (outs[1][1] + outs[1][2])


def test_enqueue_fixed_order_delay_is_harmless():
    # A 500 ms delay at the FIXED ordering's seam (handle parked,
    # Request not yet visible): nothing can negotiate an unparked
    # entry, so the world completes correctly on every rank — the
    # ordering fix's proof point.
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "core.enqueue.pre_insert:delay:0.5@rank=1",
    }, worker=FAULT_WORKER)
    _assert_ok(outs, marker="FAULT_OK")


def _skew_totals(outs):
    """{rank: (lat_sum, count)} from the delay_skew scenario's
    SKEW_TOTALS report lines."""
    totals = {}
    for rank, (_rc, out, _err) in enumerate(outs):
        for line in out.splitlines():
            if line.startswith("SKEW_TOTALS "):
                _tag, r, total, count = line.split()
                totals[int(r)] = (float(total), int(count))
    return totals


@pytest.mark.slow
def test_drain_record_delay_completes_and_skews():
    # ISSUE 12 satellite: the `delay` action at the multihost DRAIN
    # seam (mh.drain.record — a negotiated record popped, dispatch
    # stalled; until now only die/drop/wedge paths were asserted
    # here).  A delayed-but-alive rank must COMPLETE every group with
    # correct values, not error it — and the delay must show up as
    # mh_collective_seconds skew: the t0 stamp sits AFTER this seam,
    # so the delayed rank's own window stays the exec-only fleet
    # minimum while the PROMPT rank's inflates by the wait (the
    # arrival-lag inversion the skew observatory scores).
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "mh.drain.record:delay:0.2@rank=1",
        "TEST_SCENARIO": "delay_skew",
    }, worker=FAULT_WORKER)
    _assert_ok(outs, marker="FAULT_OK")
    totals = _skew_totals(outs)
    assert set(totals) == {0, 1}, totals
    # Every group completed on both ranks (delayed != dropped).
    assert totals[0][1] >= 12 and totals[1][1] >= 12, totals
    # The prompt rank absorbed most of 12 x 0.2 s of waiting; the
    # delayed rank's own latency is a small fraction of it.
    assert totals[0][0] > 12 * 0.2 * 0.5, totals
    assert totals[0][0] > 3 * totals[1][0], totals


@pytest.mark.slow
def test_enqueue_delay_completes_without_skew():
    # The ENQUEUE seam's delay (mh.enqueue.pre_register): the payload
    # registers late, so NEGOTIATION stalls — but once negotiated,
    # both executors dispatch together, so the world completes
    # correctly with no per-rank latency skew (dispatch-to-completion
    # windows stay symmetric; the cost shows up as throughput, which
    # is exactly why the observatory keys on the dispatch seam's
    # signature rather than enqueue lag).
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "mh.enqueue.pre_register:delay:0.2@rank=1",
        "TEST_SCENARIO": "delay_skew",
    }, worker=FAULT_WORKER)
    _assert_ok(outs, marker="FAULT_OK")
    totals = _skew_totals(outs)
    assert totals[0][1] >= 12 and totals[1][1] >= 12, totals


def test_drain_drop_injection_trips_watchdog():
    # mh.drain.record:drop on rank 1 = a member that negotiates but
    # never dispatches (the alive-but-absent failure the execution
    # watchdog exists for), injected instead of hand-rolled in a
    # bespoke worker: rank 0 must fail loudly within the watchdog
    # window, never hang and never return a wrong value.
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "mh.drain.record:drop@rank=1",
        "HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS": "6",
    }, worker=FAULT_WORKER)
    rc0, out0, err0 = outs[0]
    assert rc0 == 3, "rank 0 should fail loudly (rc=%d):\n%s\n%s" % (
        rc0, out0, err0)
    assert "FAULT_LOUD 0" in out0, out0
    # Rank 1 dropped the record: its own handle never resolves and the
    # engine poisons on watchdog/stopped sweep — loud there too.
    rc1, out1, _err1 = outs[1]
    assert rc1 != 0 and "FAULT_OK" not in out1, (rc1, out1)


RESILIENCE_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "utils",
    "multihost_resilience_worker.py")


def test_leg_drop_bounded_is_absorbed_by_retry():
    # ISSUE 18 acceptance: a BOUNDED transport flake on rank 1's hier
    # leg (mh.leg.drop:drop@times=2) is absorbed by the transient-retry
    # budget — every group completes with the CORRECT value on every
    # rank, the victim's retry counter shows exactly the injected
    # count, and nothing was demoted.  The worker asserts the evidence
    # in-process (resilience.describe() + the path counters).
    _assert_ok(_spawn_multihost(2, local_devices=2, extra_env={
        "HVD_TPU_FAULT": "mh.leg.drop:drop@times=2@rank=1",
        "HOROVOD_LEG_RETRY_BACKOFF": "0.01",
        "TEST_SCENARIO": "leg_flake",
    }, worker=RESILIENCE_WORKER), marker="RESILIENCE_OK")


@pytest.mark.slow
def test_leg_drop_sustained_demotes_then_repromotes():
    # ISSUE 18 acceptance: a SUSTAINED leg fault (unbounded drop, every
    # rank) exhausts the retry budget twice, rank 0's KV verdict
    # demotes (allreduce, 131072) hier->flat SPMD-uniformly, a demoted
    # dispatch routes flat with no new retries, and after the fault is
    # disarmed the 1 s re-probe window re-promotes the class — the
    # final dispatch rides hier again.  The SPMD verdict needs a
    # rendezvous KV, so the test runs one in-process.
    from horovod_tpu.runner.http_server import RendezvousServer
    server = RendezvousServer(host="127.0.0.1", secret="s")
    port = server.start()
    try:
        _assert_ok(_spawn_multihost(2, local_devices=2, extra_env={
            "HVD_TPU_FAULT": "mh.leg.drop:drop",
            "HOROVOD_LEG_MAX_RETRIES": "1",
            "HOROVOD_LEG_RETRY_BACKOFF": "0.01",
            "HOROVOD_LEG_DEMOTE_THRESHOLD": "2",
            "HOROVOD_LEG_REPROBE_SECS": "1",
            "HOROVOD_RENDEZVOUS_ADDR": "127.0.0.1:%d" % port,
            "HOROVOD_SECRET_KEY": "s",
            "TEST_SCENARIO": "leg_demote",
        }, worker=RESILIENCE_WORKER), marker="RESILIENCE_OK")
    finally:
        server.stop()


def test_deadline_wedge_expires_loudly_with_restore_shaped_error():
    # ISSUE 18 acceptance: mh.deadline.wedge withholds the dispatch of
    # a negotiated, deadline-stamped group on every rank — the exact
    # shape of a program that never starts.  The per-collective
    # deadline (4 s) must expire it: every rank fails LOUDLY with the
    # deadline-shaped HorovodInternalError, and the message must NOT
    # be the stall inspector's drain-shaped abort text (elastic keys on
    # that phrase to pick drain vs restore-from-spill).
    outs = _spawn_multihost(2, local_devices=1, extra_env={
        "HVD_TPU_FAULT": "mh.deadline.wedge:drop@times=1",
        "HOROVOD_COLLECTIVE_TIMEOUT_SECS": "4",
    }, worker=FAULT_WORKER)
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 3, "rank %d should fail loudly (rc=%d):\n%s\n%s" \
            % (rank, rc, out, err)
        assert "FAULT_LOUD %d" % rank in out, out
        assert "collective deadline exceeded" in out, out
        assert "stall shutdown threshold" not in out + err, out + err


FASTPATH_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "utils",
    "multihost_fastpath_worker.py")


def _spawn_fastpath(scenario, extra_env=None):
    # Every fast-path scenario needs the rendezvous KV: the freeze
    # verdict is rank-0-decided and KV-adopted (a KV-less multi-member
    # world never freezes by design), so run a server in-process.
    from horovod_tpu.runner.http_server import RendezvousServer
    server = RendezvousServer(host="127.0.0.1", secret="s")
    port = server.start()
    env = {
        "HOROVOD_FAST_PATH_WARM_CYCLES": "3",
        "HOROVOD_RENDEZVOUS_ADDR": "127.0.0.1:%d" % port,
        "HOROVOD_SECRET_KEY": "s",
        "TEST_SCENARIO": scenario,
    }
    env.update(extra_env or {})
    try:
        _assert_ok(_spawn_multihost(2, local_devices=2, extra_env=env,
                                    worker=FASTPATH_WORKER),
                   marker="FASTPATH_OK")
    finally:
        server.stop()


def test_fastpath_shape_change_thaws_and_refreezes():
    # ISSUE 19 acceptance: after the warm streak the engine dispatches
    # from the frozen schedule (frozen counter moves, negotiation-cycle
    # counter does not — the satellite-f reconciliation), a mismatching
    # shape thaws loudly with the correct renegotiated value, and the
    # engine re-freezes on the new shape.
    _spawn_fastpath("fp_shape")


def test_fastpath_membership_change_thaws():
    # ISSUE 19 acceptance: the elastic-resize-shaped membership change
    # (process-set removal -> engine invalidation) thaws the frozen
    # schedule with reason=membership before the engine mutates its
    # pending map; the world keeps reducing correctly and re-freezes.
    _spawn_fastpath("fp_membership")


def test_fastpath_stale_dispatch_injection_thaws():
    # ISSUE 19 acceptance (injection-certified): the armed
    # engine.fastpath.stale_dispatch site drops the first frozen bucket
    # dispatch — thaw(staleness), the staged tensor flushes back
    # through full negotiation (correct value, NO hang), and the engine
    # re-freezes after every rank disarms.
    _spawn_fastpath("fp_stale", extra_env={
        "HVD_TPU_FAULT": "engine.fastpath.stale_dispatch:drop@times=1",
    })


def test_fastpath_route_demote_verdict_thaws():
    # ISSUE 19 acceptance: the r21 degraded-route demote verdict
    # (rank 0 streak through the KV) thaws the frozen schedule on every
    # member BEFORE the plan invalidate; post-thaw dispatches
    # renegotiate onto the demoted flat route with correct values.
    _spawn_fastpath("fp_route", extra_env={
        "HVD_TPU_FAULT": "mh.leg.drop:drop",
        "HOROVOD_LEG_MAX_RETRIES": "1",
        "HOROVOD_LEG_RETRY_BACKOFF": "0.01",
        "HOROVOD_LEG_DEMOTE_THRESHOLD": "2",
    })


def test_init_detects_preinitialized_runtime(monkeypatch):
    # A pre-initialized JAX backend makes jax.distributed.initialize a
    # silent no-op: every rank would train alone while believing it is
    # rank r of N.  init_jax_distributed must detect the world that
    # failed to form and raise, not proceed.
    import types

    from horovod_tpu.common import multihost as mh

    fake_jax = types.SimpleNamespace(
        config=types.SimpleNamespace(
            update=lambda *a, **k: None, jax_platforms="cpu"),
        distributed=types.SimpleNamespace(
            initialize=lambda **kw: None),  # the silent no-op
        process_count=lambda: 1,            # world never formed
    )
    monkeypatch.setattr(mh, "init_jax_distributed",
                        mh.init_jax_distributed)
    monkeypatch.setitem(__import__("sys").modules, "jax", fake_jax)
    monkeypatch.setattr(mh.init_jax_distributed, "_done", False,
                        raising=False)
    cfg = types.SimpleNamespace(coordinator_addr="127.0.0.1:1",
                                rendezvous_addr=None, secret_key=None)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="train alone|initialized "
                                            "before|process_count"):
        mh.init_jax_distributed(cfg, rank=0, size=2)
    mh.init_jax_distributed._done = False


ZERO_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "utils",
    "zero_mh_worker.py")


@pytest.mark.slow
def test_multihost_zero23_quantized_e2e():
    # ISSUE 15: ZeRO-2/3 step builders over the REAL proc x local mesh
    # (2 procs x 2 local devices) with the int8 DCN leg armed —
    # position-dependent payloads vs a single-device reference within
    # the EF bounds, per-tensor EF residuals present, and (via
    # HVD_TPU_DUMP_HLO) the lowered programs spanning all
    # n_procs x n_local partitions with reduce-scatter/all-gather HLO
    # and an s8 wire.
    _assert_ok(_spawn_multihost(2, local_devices=2, worker=ZERO_WORKER,
                                extra_env={
        "HOROVOD_CROSS_HOST_COMPRESSION": "int8",
        "HVD_TPU_DUMP_HLO": "1",
    }))
