"""MXNet adapter tests.

Reference parity: ``test/parallel/test_mxnet1.py``/``test_mxnet2.py`` —
collectives, DistributedOptimizer gradient averaging, parameter
broadcast.  mxnet is not installed here, so the duck-typed surface is
exercised with a numpy-backed NDArray shim (the adapter binds to real
``mx.nd.NDArray`` when mxnet exists); a size-1 tcp world makes the wire
path real.
"""

import numpy as np
import pytest


class FakeNDArray:
    """Just enough of mx.nd.NDArray for the adapter: asnumpy(),
    in-place slice assignment, shape."""

    def __init__(self, arr):
        self._arr = np.array(arr, dtype=np.float32)

    def asnumpy(self):
        return self._arr.copy()

    @property
    def shape(self):
        return self._arr.shape

    def __setitem__(self, key, value):
        if isinstance(value, FakeNDArray):
            value = value._arr
        self._arr[key] = np.asarray(value)

    def _from_numpy_(self, arr):
        return FakeNDArray(arr)


@pytest.fixture(scope="module")
def hvd():
    import horovod_tpu.mxnet as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


def test_size1_collectives(hvd):
    assert hvd.size() == 1 and hvd.rank() == 0
    t = FakeNDArray(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvd.allreduce(t, op=hvd.Sum, name="mx_ar")
    assert isinstance(out, FakeNDArray)
    np.testing.assert_array_equal(out.asnumpy(), t.asnumpy())

    t2 = FakeNDArray(np.ones(3))
    hvd.allreduce_(t2, op=hvd.Average, name="mx_ar2")
    np.testing.assert_array_equal(t2.asnumpy(), np.ones(3))

    g = hvd.allgather(t, name="mx_ag")
    np.testing.assert_array_equal(g.asnumpy(), t.asnumpy())

    b = hvd.broadcast(t, root_rank=0, name="mx_bc")
    np.testing.assert_array_equal(b.asnumpy(), t.asnumpy())

    h = hvd.allreduce_async(t, name="mx_h")
    assert hvd.poll(h) in (True, False)
    hvd.synchronize(h)


def test_grouped_allreduce(hvd):
    ts = [FakeNDArray(np.full(4, i, dtype=np.float32)) for i in range(3)]
    outs = hvd.grouped_allreduce(ts, op=hvd.Sum, name="mx_gar")
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o.asnumpy(), np.full(4, i))


def test_distributed_optimizer_updates_through(hvd):
    calls = []

    class FakeOpt:
        def update(self, index, weight, grad, state):
            calls.append(("update", index))
            weight[:] = weight.asnumpy() - 0.1 * grad.asnumpy()

    opt = hvd.DistributedOptimizer(FakeOpt())
    w = FakeNDArray(np.ones(4))
    g = FakeNDArray(np.full(4, 2.0))
    opt.update(0, w, g, None)
    assert calls == [("update", 0)]
    np.testing.assert_allclose(w.asnumpy(), np.ones(4) - 0.2)

    # multi-tensor form (lists), routed through update_multi_precision
    calls.clear()
    ws = [FakeNDArray(np.ones(2)), FakeNDArray(np.zeros(2))]
    gs = [FakeNDArray(np.ones(2)), FakeNDArray(np.ones(2))]

    class FakeMultiOpt:
        def update(self, index, weight, grad, state):
            calls.append(("multi", tuple(index)))

    hvd.DistributedOptimizer(FakeMultiOpt()).update_multi_precision(
        [0, 1], ws, gs, [None, None])
    assert calls == [("multi", (0, 1))]


def test_broadcast_parameters_dict(hvd):
    params = {"w": FakeNDArray(np.arange(3, dtype=np.float32)),
              "b": FakeNDArray(np.zeros(2))}
    hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_array_equal(params["w"].asnumpy(), np.arange(3))


def test_broadcast_parameters_parameter_dict(hvd):
    class FakeParam:
        def __init__(self, arr):
            self._t = FakeNDArray(arr)

        def list_data(self):
            return [self._t]

        def data(self):
            return self._t

    pd = {"dense0_weight": FakeParam(np.ones((2, 2)))}
    hvd.broadcast_parameters(pd, root_rank=0)
    np.testing.assert_array_equal(
        pd["dense0_weight"].data().asnumpy(), np.ones((2, 2)))


def test_distributed_trainer_requires_mxnet(hvd):
    with pytest.raises(ImportError):
        hvd.DistributedTrainer(None, "sgd")


def test_broadcast_object(hvd):
    obj = {"epoch": 3, "arr": np.arange(4)}
    out = hvd.broadcast_object(obj, root_rank=0)
    assert out["epoch"] == 3
    np.testing.assert_array_equal(out["arr"], np.arange(4))
