"""Op-manager backend registry tests (reference:
``operation_manager.cc`` — priority walk, first Enabled() wins)."""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.ops.engine import CollectiveHandle
from horovod_tpu.ops.op_manager import OpRequest, order_from_env

SIZE = 8


class _FakeBackend(hvd.CollectiveBackend):
    """Accepts only allreduces whose name carries a marker prefix —
    per-tensor selection, like a DCN backend claiming big payloads."""

    name = "fake_dcn"

    def __init__(self):
        self.seen = []

    def enabled(self, req):
        return (req.op_type == "allreduce"
                and all(n.startswith("dcn.") for n in req.names))

    def submit(self, req):
        self.seen.append(list(req.names))
        hs = []
        for t, n in zip(req.tensors, req.names):
            h = CollectiveHandle(n)
            h._set_result("fake:%s" % n)
            hs.append(h)
        return hs if req.is_group else hs[0]


def test_priority_walk_and_per_tensor_selection(hvd_world):
    mgr = basics._get_op_manager()
    assert [b.name for b in mgr.backends] == ["inprocess_ici"]

    fake = _FakeBackend()
    hvd.register_backend(fake, index=0)
    try:
        # Marked tensors go to the fake backend...
        out = hvd.allreduce(np.ones((SIZE, 3), np.float32),
                            name="dcn.big")
        assert out == "fake:dcn.big"
        assert fake.seen == [["dcn.big"]]
        # ...unmarked ones fall through to the engine and really reduce.
        out = hvd.allreduce(np.ones((SIZE, 3), np.float32), name="plain",
                            op=hvd.Sum)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full(3, float(SIZE), np.float32))
        assert fake.seen == [["dcn.big"]]

        # Introspection names the winner without executing.
        req = OpRequest("allreduce", [None], ["dcn.x"], red_op=hvd.Sum)
        assert mgr.backend_for(req) == "fake_dcn"
        req = OpRequest("allgather", [None], ["dcn.x"])
        assert mgr.backend_for(req) == "inprocess_ici"
    finally:
        mgr.backends.remove(fake)


def test_group_routes_through_one_backend(hvd_world):
    fake = _FakeBackend()
    hvd.register_backend(fake, index=0)
    mgr = basics._get_op_manager()
    try:
        outs = hvd.grouped_allreduce(
            [np.ones((SIZE, 2)), np.ones((SIZE, 2))], name="dcn.grp")
        assert outs == ["fake:dcn.grp.0", "fake:dcn.grp.1"]
        assert fake.seen == [["dcn.grp.0", "dcn.grp.1"]]
    finally:
        mgr.backends.remove(fake)


def test_order_from_env_validates_names(hvd_world):
    mgr = basics._get_op_manager()
    assert [b.name for b in order_from_env(mgr.backends,
                                           "inprocess_ici")] \
        == ["inprocess_ici"]
    with pytest.raises(ValueError, match="unknown backend"):
        order_from_env(mgr.backends, "nccl")


def test_no_backend_raises(hvd_world):
    mgr = basics._get_op_manager()
    req = OpRequest("bogus_op", [None], ["x"])
    with pytest.raises(Exception, match="no enabled backend"):
        mgr.submit(req)
