"""Fused Pallas BatchNorm kernels vs an f32 XLA oracle.

Same strategy as tests/test_pallas_kernels.py: the kernels run through
the Pallas interpreter on the CPU test world, and y / dx / dgamma /
dbeta / dresidual are compared against plain-jnp BatchNorm autodiff.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_bn import _plan, batch_norm_act

EPS = 1e-5


def _oracle(x, g, b, res, relu):
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mu = jnp.mean(xf, axes)
    var = jnp.mean(jnp.square(xf), axes) - jnp.square(mu)  # biased
    z = (xf - mu) * jax.lax.rsqrt(var + EPS) * g + b
    if res is not None:
        z = z + res.astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    return z.astype(x.dtype), mu, var


@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("residual", [True, False])
def test_bn_act_matches_oracle(relu, residual):
    rng = np.random.RandomState(0)
    shape = (16, 4, 4, 64)  # M = 256, C = 64
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    g = jnp.asarray(rng.randn(64), jnp.float32)
    b = jnp.asarray(rng.randn(64), jnp.float32)
    res = (jnp.asarray(rng.randn(*shape), jnp.float32)
           if residual else None)
    w = jnp.asarray(rng.randn(*shape), jnp.float32)

    def loss_pallas(x, g, b, res):
        out = batch_norm_act(x, g, b, res, eps=EPS, relu=relu)
        assert out is not None
        y, mean, var = out
        return jnp.sum(y * w), (y, mean, var)

    def loss_oracle(x, g, b, res):
        y, mean, var = _oracle(x, g, b, res, relu)
        return jnp.sum(y * w), (y, mean, var)

    (lp, (yp, mp, vp)), gp = jax.value_and_grad(
        loss_pallas, argnums=(0, 1, 2) + ((3,) if residual else ()),
        has_aux=True)(x, g, b, res)
    (lo, (yo, mo, vo)), go = jax.value_and_grad(
        loss_oracle, argnums=(0, 1, 2) + ((3,) if residual else ()),
        has_aux=True)(x, g, b, res)

    np.testing.assert_allclose(yp, yo, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(mp, mo, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(vp, vo, rtol=1e-4, atol=1e-5)
    for got, want, name in zip(gp, go, ["dx", "dgamma", "dbeta",
                                        "dres"]):
        np.testing.assert_allclose(
            got, want, rtol=5e-4, atol=5e-5,
            err_msg="%s mismatch (relu=%s residual=%s)"
                    % (name, relu, residual))


def test_bn_act_bf16():
    rng = np.random.RandomState(1)
    shape = (8, 8, 8, 128)  # M = 512, C = 128
    x = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    g = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(128), jnp.float32)

    def loss(x):
        y, _, _ = batch_norm_act(x, g, b, None, eps=EPS, relu=True)
        assert y.dtype == jnp.bfloat16
        return jnp.sum(y.astype(jnp.float32))

    def loss_o(x):
        y, _, _ = _oracle(x, g, b, None, True)
        return jnp.sum(y.astype(jnp.float32))

    gx = jax.grad(loss)(x)
    go = jax.grad(loss_o)(x)
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(go, np.float32),
                               rtol=0.1, atol=0.05)


def test_plan_fallback():
    # Prime M / odd C: no legal tiling -> caller falls back to XLA.
    assert _plan(997, 64) is None
    assert _plan(1024, 100) is None
    # C=64 folds 2 rows into one 128-lane row.
    assert _plan(1024, 64) == (2, 128)
    # 128*7*7 channels-2048 case from ResNet-50's last stage.
    assert _plan(6272, 2048) == (1, 256)
    x = jnp.ones((997, 64), jnp.float32)
    assert batch_norm_act(x, jnp.ones(64), jnp.zeros(64)) is None
