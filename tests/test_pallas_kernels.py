"""Pallas kernel tests (interpret mode on the CPU world; the same
kernel code compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.pallas_kernels import (flash_attention,
                                            fused_scale_sum,
                                            _reference_attention)


def _qkv(b=2, s=128, h=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal)
    want = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_blocks_span_sequence():
    # seq 256 → multiple q and k blocks; checks the online-softmax
    # accumulation across blocks
    q, k, v = _qkv(b=1, s=256, h=1, d=64, seed=1)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_irregular_seq_falls_back():
    q, k, v = _qkv(b=1, s=96, h=1, d=16, seed=2)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grad(causal):
    # The Pallas backward (dq AND dk/dv kernels) against autodiff of
    # the reference oracle.
    q, k, v = _qkv(b=1, s=128, h=2, d=32, seed=3)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grad_multiblock_grid(causal):
    # s=192 -> 3x3 grid of 64-blocks: exercises cross-block scratch
    # accumulation, the init/finish grid boundaries, and the causal
    # block-live skip in BOTH backward kernels (s=128 is a 1x1 grid
    # where those paths degenerate).
    q, k, v = _qkv(b=1, s=192, h=2, d=32, seed=7)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


def test_flash_attention_grad_chunked_escape_hatch(monkeypatch):
    # HVD_TPU_FLASH_BWD=chunked selects the XLA chunked backward; both
    # paths must match the oracle.
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", "chunked")
    q, k, v = _qkv(b=1, s=128, h=1, d=32, seed=5)

    def loss_flash(q_):
        return jnp.sum(flash_attention(q_, k, v, causal=True) ** 2)

    def loss_ref(q_):
        return jnp.sum(_reference_attention(q_, k, v, True) ** 2)

    g1 = jax.grad(loss_flash)(q)
    g2 = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=2e-4, rtol=2e-4)


def test_fused_scale_sum():
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(3, 50), jnp.float32)  # non-lane-aligned
    b = jnp.asarray(rng.randn(3, 50), jnp.float32)
    got = fused_scale_sum(a, b, alpha=0.5, beta=2.0)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(0.5 * a + 2.0 * b),
                               atol=1e-6)


def test_flash_attention_gqa():
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 128, 4, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, jnp.repeat(k, 2, 2),
                                jnp.repeat(v, 2, 2), True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
