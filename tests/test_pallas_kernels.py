"""Pallas kernel tests (interpret mode on the CPU world; the same
kernel code compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import pallas_kernels as pk
from horovod_tpu.ops.pallas_kernels import (flash_attention,
                                            fused_scale_sum,
                                            _reference_attention)


def _qkv(b=2, s=128, h=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal)
    want = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_blocks_span_sequence():
    # seq 256 → multiple q and k blocks; checks the online-softmax
    # accumulation across blocks
    q, k, v = _qkv(b=1, s=256, h=1, d=64, seed=1)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_irregular_seq_falls_back():
    q, k, v = _qkv(b=1, s=96, h=1, d=16, seed=2)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grad(causal):
    # The Pallas backward (dq AND dk/dv kernels) against autodiff of
    # the reference oracle.
    q, k, v = _qkv(b=1, s=128, h=2, d=32, seed=3)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grad_multiblock_grid(causal):
    # s=192 -> 3x3 grid of 64-blocks: exercises cross-block scratch
    # accumulation, the init/finish grid boundaries, and the causal
    # block-live skip in BOTH backward kernels (s=128 is a 1x1 grid
    # where those paths degenerate).
    q, k, v = _qkv(b=1, s=192, h=2, d=32, seed=7)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


# The r9 kernel grid: both Pallas backward structures (two-pass dq/dkv
# and the fused one-pass with dq partials) across block shapes, causal
# on/off, and a lane-padded vs exact head dim — the interpret-mode
# numerics net under any kernel restructure.  Block shapes are driven
# through the HVD_TPU_FLASH_BLOCK_Q/K hooks, exactly how an A/B or the
# autotune sweep drives them.
@pytest.mark.parametrize(
    "variant,block_q,block_k,causal",
    # full causal coverage over the block pairs; non-causal once per
    # variant (the masking branch is the only causal-sensitive code,
    # and interpret-mode grads are the expensive part of tier-1)
    [(v, bq, bk, True) for v in ("pallas", "pallas_onepass")
     for bq, bk in ((64, 128), (128, 64), (128, 128))]
    + [(v, 128, 128, False) for v in ("pallas", "pallas_onepass")])
def test_flash_bwd_grid(monkeypatch, variant, block_q, block_k, causal):
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", variant)
    monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_Q", str(block_q))
    monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_K", str(block_k))
    q, k, v = _qkv(b=1, s=128, h=1, d=32, seed=11)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


@pytest.mark.parametrize("variant", ["pallas", "pallas_onepass"])
def test_flash_bwd_grid_exact_lane_dim(monkeypatch, variant):
    # d=128: no lane padding (d_pad == d) — the zero-column path of the
    # d=32 grid above must not be the only covered layout.
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", variant)
    q, k, v = _qkv(b=1, s=128, h=1, d=128, seed=12)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


def test_flash_bwd_onepass_multiblock_grid(monkeypatch):
    # s=192 -> 3x3 grid of 64-blocks: the one-pass kernel's scratch
    # accumulation, dead-tile zero write, and partial-dq reduce across
    # a grid where causal skipping actually fires.
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", "pallas_onepass")
    q, k, v = _qkv(b=1, s=192, h=2, d=32, seed=13)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_reference_attention(q_, k_, v_, True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, lbl in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg="d%s mismatch" % lbl)


def test_flash_bwd_unknown_variant_fails_loudly(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", "onepass")  # typo'd value
    q, k, v = _qkv(b=1, s=128, h=1, d=32, seed=14)
    with pytest.raises(ValueError, match="HVD_TPU_FLASH_BWD"):
        jax.grad(lambda q_: jnp.sum(
            flash_attention(q_, k, v, causal=True) ** 2))(q)


def test_autotune_flash_blocks_pins_plan(monkeypatch):
    # The sweep measures each candidate and PINS the winner into the
    # plan registry: _plan must consult it, and env overrides must win
    # over (and suppress) the pin.
    monkeypatch.delenv("HVD_TPU_FLASH_BLOCK_Q", raising=False)
    monkeypatch.delenv("HVD_TPU_FLASH_BLOCK_K", raising=False)
    try:
        info = pk.autotune_flash_blocks(
            128, 32, batch_heads=1, iters=1, include_bwd=False,
            candidates=[(64, 64), (128, 128)], report_core=False)
        assert info["pinned"], info
        assert info["best"] in info["candidates"]
        assert pk._TUNED_BLOCKS[(128, 128)] == info["best"]
        plan = pk.flash_plan_info(128, 32)
        assert plan["source"] == "autotuned"
        assert (plan["block_q"], plan["block_k"]) == info["best"]
        # tuned blocks still produce oracle-exact attention
        q, k, v = _qkv(b=1, s=128, h=1, d=32, seed=15)
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, causal=True)),
            np.asarray(_reference_attention(q, k, v, True)),
            atol=2e-5, rtol=2e-5)
        # an explicit env A/B wins over the tuner and suppresses pinning
        monkeypatch.setenv("HVD_TPU_FLASH_BLOCK_Q", "64")
        assert pk.flash_plan_info(128, 32)["source"] == "env"
        info2 = pk.autotune_flash_blocks(
            128, 32, batch_heads=1, iters=1, include_bwd=False,
            candidates=[(64, 64)], report_core=False)
        assert not info2["pinned"]
    finally:
        pk._TUNED_BLOCKS.clear()


def test_kernel_tuner_native_mirror():
    # The C++ KernelTuner (core/src/parameter_manager.cc) must agree
    # with the Python KernelBlockTuner on argmax-by-mean.
    pytest.importorskip("ctypes")
    from horovod_tpu.core.client import (core_library_available,
                                         load_library)
    if not core_library_available():
        pytest.skip("native core not buildable here")
    lib = load_library()
    base = lib.hvd_tcp_kernel_tune_samples()
    # Huge scores so this test's choices dominate any samples another
    # in-process test may have recorded into the singleton tuner.
    lib.hvd_tcp_kernel_tune_record(7, 1.0e18)
    lib.hvd_tcp_kernel_tune_record(9, 3.0e18)
    lib.hvd_tcp_kernel_tune_record(9, 5.0e18)
    lib.hvd_tcp_kernel_tune_record(7, 10.0e18)  # mean 5.5e18 beats 4e18
    assert lib.hvd_tcp_kernel_tune_best() == 7
    assert lib.hvd_tcp_kernel_tune_samples() == base + 4


def test_flash_attention_grad_chunked_escape_hatch(monkeypatch):
    # HVD_TPU_FLASH_BWD=chunked selects the XLA chunked backward; both
    # paths must match the oracle.
    monkeypatch.setenv("HVD_TPU_FLASH_BWD", "chunked")
    q, k, v = _qkv(b=1, s=128, h=1, d=32, seed=5)

    def loss_flash(q_):
        return jnp.sum(flash_attention(q_, k, v, causal=True) ** 2)

    def loss_ref(q_):
        return jnp.sum(_reference_attention(q_, k, v, True) ** 2)

    g1 = jax.grad(loss_flash)(q)
    g2 = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=2e-4, rtol=2e-4)


def test_fused_scale_sum():
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(3, 50), jnp.float32)  # non-lane-aligned
    b = jnp.asarray(rng.randn(3, 50), jnp.float32)
    got = fused_scale_sum(a, b, alpha=0.5, beta=2.0)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(0.5 * a + 2.0 * b),
                               atol=1e-6)


def test_flash_attention_gqa():
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 128, 4, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    got = flash_attention(q, k, v, causal=True)
    want = _reference_attention(q, jnp.repeat(k, 2, 2),
                                jnp.repeat(v, 2, 2), True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
