"""Collective-plan cache tests (utils/plancache.py): blob codec +
atomic persistence, loud fallbacks for corrupt/mismatched blobs, KV
publish/adopt, per-class routing precedence (env wins and suppresses
pinning, the r9 flash-block convention), the PlanTuner GP sweep unit,
the crash-safe AutotuneLog writer, and the slow-marked cold-vs-warm
2-proc e2e the CI perf-smoke step runs by node id."""

import json
import logging
import os
import threading
import types

import pytest

from horovod_tpu.common import metrics
from horovod_tpu.common.config import Config
from horovod_tpu.utils import plancache
from horovod_tpu.utils.autotune import (AutotuneLog, ParameterManager,
                                        PlanTuner)

FP = plancache.topology_fingerprint(2, 4, "TPU v5e")


@pytest.fixture(autouse=True)
def _fresh_planes():
    from horovod_tpu.ops import pallas_kernels as pk
    saved_blocks = dict(pk._TUNED_BLOCKS)
    metrics.reset()
    plancache.reset()
    yield
    metrics.reset()
    plancache.reset()
    pk._TUNED_BLOCKS.clear()
    pk._TUNED_BLOCKS.update(saved_blocks)


def _plan(fingerprint=FP):
    plan = plancache.empty_plan(fingerprint)
    plan["tuned"] = {"fusion_threshold": 1 << 25,
                     "cycle_time_ms": 3.5, "converged": True}
    plan["collectives"] = {
        "allreduce": {"20": {"path": "hier", "codec": "int8"},
                      "12": {"path": "flat", "codec": "none"}}}
    plan["flash_blocks"] = {"512x128": [256, 512]}
    return plan


# -- blob codec + on-disk roundtrip ----------------------------------------

def test_roundtrip_and_hit_counter(tmp_path):
    plan = _plan()
    path = plancache.store(plan, str(tmp_path))
    assert path and os.path.exists(path)
    assert plancache.load(str(tmp_path), FP) == plan
    assert metrics.series_sum("plan_cache_hits_total") == 1
    assert metrics.series_sum("plan_cache_misses_total") == 0


def test_absent_blob_is_a_miss(tmp_path):
    assert plancache.load(str(tmp_path), FP) is None
    assert metrics.series_sum("plan_cache_misses_total") == 1


def test_corrupt_crc_falls_back_loudly(tmp_path, caplog):
    path = plancache.store(_plan(), str(tmp_path))
    with open(path, "r+b") as f:
        f.seek(-3, os.SEEK_END)
        f.write(b"XXX")
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        assert plancache.load(str(tmp_path), FP) is None
    assert "ignoring unusable plan cache" in caplog.text
    assert metrics.series_sum("plan_cache_misses_total") == 1
    assert metrics.series_sum("plan_cache_hits_total") == 0


def test_version_mismatch_falls_back_loudly(tmp_path, caplog):
    blob = plancache.encode(_plan())
    head = plancache._HEADER.unpack(
        blob[len(plancache.MAGIC):
             len(plancache.MAGIC) + plancache._HEADER.size])
    bad = (plancache.MAGIC
           + plancache._HEADER.pack(plancache.SCHEMA_VERSION + 1,
                                    *head[1:])
           + blob[len(plancache.MAGIC) + plancache._HEADER.size:])
    with pytest.raises(plancache.PlanCacheInvalid, match="schema"):
        plancache.decode(bad)
    path = plancache.plan_path(str(tmp_path), FP)
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(bad)
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        assert plancache.load(str(tmp_path), FP) is None
    assert "falling back to default plans" in caplog.text


def test_torn_payload_and_bad_magic_rejected():
    blob = plancache.encode(_plan())
    with pytest.raises(plancache.PlanCacheInvalid, match="torn"):
        plancache.decode(blob[:-4])
    with pytest.raises(plancache.PlanCacheInvalid, match="magic"):
        plancache.decode(b"NOTAPLAN" + blob)
    with pytest.raises(plancache.PlanCacheInvalid, match="magic"):
        plancache.decode(b"")


def test_fingerprint_mismatch_is_a_loud_miss(tmp_path, caplog):
    other = plancache.topology_fingerprint(8, 4, "TPU v4")
    plan = _plan(other)
    # Land the wrong-fingerprint blob at THIS fingerprint's path (a
    # copied cache dir from another pod shape).
    blob_path = plancache.plan_path(str(tmp_path), FP)
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(blob_path, "wb") as f:
        f.write(plancache.encode(plan))
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        assert plancache.load(str(tmp_path), FP) is None
    assert "claims fingerprint" in caplog.text
    assert metrics.series_sum("plan_cache_misses_total") == 1


def test_concurrent_writers_always_leave_a_complete_blob(tmp_path):
    # N threads store distinct plans concurrently; every intermediate
    # and final state of the cache file must decode (tmp + os.replace:
    # last complete blob wins, readers never see a torn write).
    plans = []
    for i in range(8):
        p = _plan()
        p["tuned"]["fusion_threshold"] = 1 << (20 + i)
        plans.append(p)
    errs = []

    def write(p):
        try:
            assert plancache.store(p, str(tmp_path))
        except Exception as exc:  # noqa: BLE001 - collected for assert
            errs.append(exc)

    threads = [threading.Thread(target=write, args=(p,)) for p in plans]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    loaded = plancache.load(str(tmp_path), FP)
    assert loaded in plans  # one complete winner, never a mix
    leftovers = [f for f in os.listdir(str(tmp_path))
                 if f.startswith(".tmp-plan-")]
    assert leftovers == []


def test_store_into_unwritable_dir_degrades(tmp_path, caplog):
    # A regular file where the cache dir should be: makedirs fails with
    # an OSError on every platform (chmod tricks don't bind root).
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    target = str(blocker / "cache")
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        assert plancache.store(_plan(), target) is None
    assert "plan-cache write" in caplog.text


def test_topology_fingerprint_sanitizes_device_kind():
    assert plancache.topology_fingerprint(2, 4, "TPU v5 lite/pod") == \
        "p2-l4-TPU_v5_lite_pod"
    assert plancache.topology_fingerprint(1, 1, "") == "p1-l1-unknown"


# -- fleet sharing over the rendezvous KV ----------------------------------

class _FakeKV:
    def __init__(self):
        self.store = {}
        self.put_fail = False

    def put_json(self, key, obj):
        if self.put_fail:
            raise OSError("kv down")
        self.store[key] = json.dumps(obj, sort_keys=True)

    def get_json(self, key):
        v = self.store.get(key)
        return json.loads(v) if v is not None else None

    def get_blocking(self, key, timeout=60.0):
        if key not in self.store:
            raise TimeoutError("no key %s" % key)
        return self.store[key]


def test_kv_publish_then_adopt_roundtrip():
    kv = _FakeKV()
    plan = _plan()
    plancache.publish_kv(kv, plan)
    assert plancache.adopt_kv(kv, FP, timeout=0.1) == plan


def test_kv_adopt_timeout_and_torn_blob_degrade(caplog):
    kv = _FakeKV()
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        assert plancache.adopt_kv(kv, FP, timeout=0.05) is None
    assert "using default plans" in caplog.text
    # A published blob for the WRONG fingerprint must not be adopted.
    other = plancache.topology_fingerprint(9, 9, "x")
    kv.store[plancache._KV_KEY % (plancache.SCHEMA_VERSION, FP)] = \
        json.dumps(_plan(other))
    assert plancache.adopt_kv(kv, FP, timeout=0.05) is None


def test_kv_publish_failure_never_raises(caplog):
    kv = _FakeKV()
    kv.put_fail = True
    with caplog.at_level(logging.WARNING, "horovod_tpu.plancache"):
        plancache.publish_kv(kv, _plan())  # must not raise
    assert "plan KV publish failed" in caplog.text


# -- per-class routing controller ------------------------------------------

def _controller(env_pinned=False, codec="int8", hier_available=True,
                plan=None):
    return plancache.PlanController(
        FP, plan if plan is not None else _plan(), "cache", codec,
        hier_available=hier_available, env_pinned=env_pinned)


def test_route_precedence_cache_then_default():
    ctl = _controller()
    # Cached class: the plan's decision wins over the gate's answer.
    assert ctl.route("allreduce", "20", False) == (True, True)
    assert ctl.route("allreduce", "12", True) == (False, False)
    # Unknown class: fall back to the global gate's answer.
    assert ctl.route("allreduce", "27", True) == (True, True)
    assert ctl.route("allreduce", "8", False) == (False, True)
    # Counted once per (op, size_class) resolution: two cached
    # classes, two default classes.
    assert metrics.series_sum("plan_apply_total", source="cache") == 2
    assert metrics.series_sum("plan_apply_total", source="default") == 2
    # Re-routing an already-counted class does not double count.
    ctl.route("allreduce", "20", False)
    assert metrics.series_sum("plan_apply_total", source="cache") == 2


def test_route_pin_wins_over_cached_plan():
    ctl = _controller()
    assert ctl.pin("allreduce", "20", {"path": "flat", "codec": "none"})
    assert ctl.route("allreduce", "20", True) == (False, False)
    assert metrics.series_sum("plan_apply_total", source="tuned") == 1
    table = ctl.decisions()
    assert table["allreduce"]["20"] == {
        "path": "flat", "codec": "none", "source": "tuned"}


def test_env_pins_suppress_plan_and_pinning():
    # The r9 flash-block convention: an explicit operator gate env
    # wins over any persisted plan AND refuses tuner pinning.
    ctl = _controller(env_pinned=True)
    assert ctl.route("allreduce", "12", True) == (True, True)
    assert ctl.route("allreduce", "20", False) == (False, True)
    assert ctl.pin("allreduce", "20",
                   {"path": "hier", "codec": "int8"}) is False
    assert metrics.series_sum("plan_apply_total", source="default") == 2
    assert metrics.series_sum("plan_apply_total", source="cache") == 0


def test_invalidate_under_concurrent_route_hammer():
    # ISSUE 18: the resilience demotion path calls invalidate() + pin()
    # from the SPMD check while dispatch threads race route() on the
    # same class (the memoized lock-free fast path).  No resolution may
    # tear (every answer is a well-formed pair from SOME consistent
    # state) and the settled answer must match the last verdict.
    ctl = _controller()
    stop = threading.Event()
    errors = []

    def dispatcher():
        while not stop.is_set():
            try:
                hier, codec_on = ctl.route("allreduce", "20", True)
                # flat pin -> (False, False); re-resolved cached entry
                # or default -> hier with codec.  Nothing else exists.
                assert (hier, codec_on) in ((False, False),
                                            (True, True)), \
                    (hier, codec_on)
            except Exception as exc:  # noqa: BLE001 — the assertion
                errors.append(exc)
                return

    threads = [threading.Thread(target=dispatcher) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            # demote: drop the entry, pin flat (the _apply_route pair)
            ctl.invalidate("allreduce", "20")
            ctl.pin("allreduce", "20", {"path": "flat", "codec": "none"})
            # promote: invalidate drops the pin, route re-resolves
            ctl.invalidate("allreduce", "20")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors[0]
    assert ctl.route("allreduce", "20", True) == (True, True)
    ctl.invalidate("allreduce", "20")
    ctl.pin("allreduce", "20", {"path": "flat", "codec": "none"})
    assert ctl.route("allreduce", "20", True) == (False, False)


def test_route_hier_unavailable_world_never_routes_hier():
    ctl = _controller(hier_available=False)
    use_hier, _ = ctl.route("allreduce", "20", False)
    assert use_hier is False


def test_codec_engagement_requires_matching_world_codec():
    # Plan says int8 but this world runs uncompressed: the cached path
    # choice survives, the codec engagement does not.
    ctl = _controller(codec="none")
    assert ctl.route("allreduce", "20", False) == (True, False)


def test_force_overrides_every_class_until_cleared():
    ctl = _controller()
    ctl.force({"path": "flat", "codec": "none"})
    assert ctl.route("allreduce", "20", True) == (False, False)
    ctl.force({"path": "hier", "codec": "int8"})
    assert ctl.route("allreduce", "12", False) == (True, True)
    ctl.force(None)
    assert ctl.route("allreduce", "20", False) == (True, True)
    assert ctl.last_class("allreduce") == "20"


def test_export_collectives_merges_seen_and_pinned():
    ctl = _controller()
    ctl.route("allreduce", "20", False)
    ctl.pin("broadcast", "16", {"path": "hier", "codec": "none"})
    exported = ctl.export_collectives()
    assert exported["allreduce"]["20"] == {"path": "hier",
                                           "codec": "int8"}
    assert exported["broadcast"]["16"] == {"path": "hier",
                                           "codec": "none"}
    assert "source" not in exported["allreduce"]["20"]


# -- bootstrap / finalize lifecycle ----------------------------------------

def _topo(rank=0, size=1):
    return types.SimpleNamespace(rank=rank, size=size)


def test_bootstrap_applies_tuned_point_and_counts(tmp_path, monkeypatch):
    for var in ("HVD_TPU_FUSION_THRESHOLD", "HOROVOD_FUSION_THRESHOLD",
                "HVD_TPU_CYCLE_TIME", "HOROVOD_CYCLE_TIME"):
        monkeypatch.delenv(var, raising=False)
    plancache.store(_plan(plancache.topology_fingerprint(1, 1, "host")),
                    str(tmp_path))
    cfg = Config(plan_cache_dir=str(tmp_path))
    plan = plancache.bootstrap(cfg, _topo(), mode="tcp")
    assert plan is not None
    assert cfg.fusion_threshold_bytes == 1 << 25
    assert cfg.cycle_time_ms == 3.5
    assert plancache.tuned_warm_start() == (1 << 25, 3.5, True)
    assert metrics.series_sum("plan_cache_hits_total") == 1
    assert metrics.series_sum("plan_apply_total", source="cache") == 1


def test_bootstrap_env_wins_over_tuned_point(tmp_path, monkeypatch):
    plancache.store(_plan(plancache.topology_fingerprint(1, 1, "host")),
                    str(tmp_path))
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(1 << 26))
    cfg = Config(plan_cache_dir=str(tmp_path),
                 fusion_threshold_bytes=1 << 26)
    plancache.bootstrap(cfg, _topo(), mode="tcp")
    assert cfg.fusion_threshold_bytes == 1 << 26  # env untouched
    assert plancache.tuned_warm_start() is None   # warm start suppressed


def test_bootstrap_disabled_or_dirless_is_inert(tmp_path):
    assert plancache.bootstrap(Config(), _topo(), mode="tcp") is None
    assert plancache.tuned_warm_start() is None
    cfg = Config(plan_cache=False, plan_cache_dir=str(tmp_path))
    assert plancache.bootstrap(cfg, _topo(), mode="tcp") is None


def test_bootstrap_multihost_without_kv_drops_local_plan(
        tmp_path, caplog, monkeypatch):
    """Regression for the spmd-uniform finding: a multihost world with
    a plan dir but NO rendezvous KV used to apply each host's local
    cache blob to routing — per-host files can differ (independent
    disks, one stale rerun), which is the r14 divergent-routing hang
    class.  The blob must be dropped, loudly."""
    for var in ("HVD_TPU_FUSION_THRESHOLD", "HOROVOD_FUSION_THRESHOLD",
                "HVD_TPU_CYCLE_TIME", "HOROVOD_CYCLE_TIME"):
        monkeypatch.delenv(var, raising=False)
    # Resolve the fingerprint exactly as bootstrap will for this host.
    plancache.bootstrap(Config(plan_cache_dir=str(tmp_path)),
                        _topo(size=4), mode="multihost")
    fp = plancache._plane.fingerprint
    plancache.reset()
    plancache.store(_plan(fp), str(tmp_path))
    cfg = Config(plan_cache_dir=str(tmp_path))
    defaults = (cfg.fusion_threshold_bytes, cfg.cycle_time_ms)
    with caplog.at_level(logging.WARNING):
        plan = plancache.bootstrap(cfg, _topo(rank=1, size=4),
                                   mode="multihost")
    assert plan is not None and not plancache.plan_has_content(plan)
    assert plancache.tuned_warm_start() is None
    assert (cfg.fusion_threshold_bytes, cfg.cycle_time_ms) == defaults
    assert "no rendezvous KV" in caplog.text
    # The controller exists but routes by the EMPTY (agreed) plan.
    ctl = plancache._plane.controller
    assert ctl is not None and ctl.route("allreduce", "20", True)[0] \
        is True
    plancache.reset()
    # tcp mode keeps its local view: no routing controller to diverge,
    # fusion/cycle pacing is per-process by design there.
    cfg_tcp = Config(plan_cache_dir=str(tmp_path))
    plancache.store(_plan(plancache.topology_fingerprint(4, 1, "host")),
                    str(tmp_path))
    plancache.bootstrap(cfg_tcp, _topo(rank=1, size=4), mode="tcp")
    assert plancache.tuned_warm_start() is not None


def test_finalize_persists_inprocess_tuner_point(tmp_path):
    cfg = Config(plan_cache_dir=str(tmp_path))
    plancache.bootstrap(cfg, _topo(), mode="tcp")
    pm = ParameterManager(1 << 23, 7.0)
    pm.frozen = True
    pm._samples_done = 5  # converged by live tuning this run
    engine = types.SimpleNamespace(parameter_manager=pm)
    plancache.finalize(tcp_core=None, engine=engine)
    loaded = plancache.load(
        str(tmp_path), plancache.topology_fingerprint(1, 1, "host"))
    assert loaded is not None
    assert loaded["tuned"] == {"fusion_threshold": 1 << 23,
                               "cycle_time_ms": 7.0, "converged": True}
    assert metrics.series_sum("plan_apply_total", source="tuned") == 1


def test_finalize_warm_started_frozen_pm_is_not_restamped_as_tuned(
        tmp_path):
    # A PM born frozen from a cache warm start sampled nothing: its
    # point is cached provenance, and finalize must not re-stage it as
    # "tuned" (that would corrupt plan_apply_total's provenance and
    # bench attribution).  The loaded plan still persists unchanged
    # through the merge.
    fp = plancache.topology_fingerprint(1, 1, "host")
    plancache.store(_plan(fp), str(tmp_path))
    cfg = Config(plan_cache_dir=str(tmp_path))
    plancache.bootstrap(cfg, _topo(), mode="tcp")
    pm = ParameterManager(1 << 26, 5.0,
                          warm_start=plancache.tuned_warm_start())
    assert pm.frozen and pm.samples_done == 0
    engine = types.SimpleNamespace(parameter_manager=pm)
    plancache.finalize(tcp_core=None, engine=engine)
    assert metrics.series_sum("plan_apply_total", source="tuned") == 0
    loaded = plancache.load(str(tmp_path), fp)
    assert loaded["tuned"]["fusion_threshold"] == 1 << 25  # unchanged


def test_finalize_without_content_writes_nothing(tmp_path):
    cfg = Config(plan_cache_dir=str(tmp_path))
    plancache.bootstrap(cfg, _topo(), mode="tcp")
    plancache.finalize(tcp_core=None, engine=None)
    assert [f for f in os.listdir(str(tmp_path))
            if f.endswith(plancache._SUFFIX)] == []


def test_describe_reports_levers_plan_schema(tmp_path):
    cfg = Config(plan_cache_dir=str(tmp_path))
    plancache.bootstrap(cfg, _topo(), mode="tcp")
    out = plancache.describe()
    assert out["enabled"] is True
    assert out["schema"] == plancache.SCHEMA_VERSION
    assert out["dir"] == str(tmp_path)
    assert out["fingerprint"] == plancache.topology_fingerprint(
        1, 1, "host")
    assert set(out["apply"]) == {"cache", "kv", "tuned", "default"}


# -- PlanTuner (GP/EI over the candidate plan grid) ------------------------

def test_plan_tuner_bootstraps_every_candidate_once():
    t = PlanTuner([(0.0, 0.0), (1.0, 0.0), (1.0, 1.0)])
    seen = []
    for _ in range(3):
        i = t.propose()
        t.record(i, 1.0)
        seen.append(i)
    assert sorted(seen) == [0, 1, 2]


def test_plan_tuner_converges_to_best_mean():
    scores = [1.0, 3.0, 2.0]
    t = PlanTuner([(0.0, 0.0), (1.0, 0.0), (1.0, 1.0)], max_samples=8)
    while not t.converged:
        i = t.propose()
        t.record(i, scores[i])
    assert t.best() == 1
    means = t.mean_scores()
    assert means[1] == 3.0


def test_plan_tuner_single_candidate_and_bad_index():
    t = PlanTuner([(0.0, 0.0)])
    assert not t.converged
    t.record(t.propose(), 5.0)
    assert t.converged and t.best() == 0
    with pytest.raises(IndexError):
        t.record(7, 1.0)
    with pytest.raises(ValueError):
        PlanTuner([])


# -- crash-safe autotune log (the satellite bugfix) ------------------------

def test_autotune_log_rank_stamped_and_append(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "3")
    base = str(tmp_path / "at.csv")
    log = AutotuneLog(base)
    assert log.path == base + ".r3"
    log.write_line("1,2,3.0,4.0")
    log.close()
    # Reopen: appends, header not restamped.
    log2 = AutotuneLog(base)
    log2.write_line("2,3,4.0,5.0")
    log2.close()
    lines = open(base + ".r3").read().splitlines()
    assert lines == [AutotuneLog.HEADER, "1,2,3.0,4.0", "2,3,4.0,5.0"]


def test_autotune_log_pid_fallback_and_bad_path(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    log = AutotuneLog(str(tmp_path / "at.csv"))
    assert log.path.endswith(".pid%d" % os.getpid())
    log.close()
    # Unwritable path: degrade to a no-op writer, never raise.
    bad = AutotuneLog(str(tmp_path / "no" / "such" / "dir" / "x.csv"))
    bad.write_line("ignored")
    bad.close()


def test_parameter_manager_writes_through_autotune_log(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "0")
    base = str(tmp_path / "pm.csv")
    pm = ParameterManager(1 << 26, 5.0, log_path=base, warmup=0,
                          steps_per_sample=1, max_samples=2)
    for _ in range(8):
        pm.observe(1 << 20, 0.001)
    del pm  # close is implicit via fd lifetime; file already flushed
    content = open(base + ".r0").read()
    assert content.startswith(AutotuneLog.HEADER)
    assert "# converged:" in content


def test_parameter_manager_warm_start_skips_warmup_and_freezes():
    pm = ParameterManager(1 << 26, 5.0, warmup=3,
                          warm_start=(1 << 24, 2.0, True))
    assert pm.fusion_threshold == 1 << 24
    assert pm.cycle_time_ms == 2.0
    assert pm.warmup == 0 and pm.frozen
    before = (pm.fusion_threshold, pm.cycle_time_ms)
    for _ in range(50):
        pm.observe(1 << 20, 0.001)
    assert (pm.fusion_threshold, pm.cycle_time_ms) == before
    assert pm.samples_done == 0


def test_parameter_manager_unconverged_warm_start_keeps_sampling():
    pm = ParameterManager(1 << 26, 5.0, warmup=3, steps_per_sample=1,
                          max_samples=30,
                          warm_start=(1 << 24, 2.0, False))
    # Unconverged: ONE warm-up cycle survives (the rerun's first
    # observation is compile-skewed and must not enter the GP), then
    # sampling resumes — still strictly fewer warm-ups than cold (3).
    assert pm.warmup == 1 and not pm.frozen
    # The adopted operating point stays live through the warm-up.
    pm.observe(1 << 20, 0.001)
    assert pm.fusion_threshold == 1 << 24
    for _ in range(4):
        pm.observe(1 << 20, 0.001)
    assert pm.samples_done > 0  # tuning resumed after the warm-up


def test_route_memo_invalidated_by_pin():
    ctl = _controller()
    assert ctl.route("allreduce", "27", True) == (True, True)  # default
    # Memoized fast path returns the same resolution...
    assert ctl.route("allreduce", "27", True) == (True, True)
    # ...until a pin changes it.
    ctl.pin("allreduce", "27", {"path": "flat", "codec": "none"})
    assert ctl.route("allreduce", "27", True) == (False, False)


# -- KV-bootstrapped worlds (fake client via monkeypatch) ------------------

class _FakeRendezvous(_FakeKV):
    calls = []

    def __init__(self, addr, secret=None):
        super().__init__()
        self.store = _FakeRendezvous.shared
        _FakeRendezvous.calls.append(addr)


def _kv_world(monkeypatch, shared=None):
    from horovod_tpu.runner import http_client
    _FakeRendezvous.shared = shared if shared is not None else {}
    _FakeRendezvous.calls = []
    monkeypatch.setattr(http_client, "RendezvousClient",
                        _FakeRendezvous)
    return _FakeRendezvous.shared


def test_bootstrap_kv_only_plane_without_cache_dir(monkeypatch):
    # Ephemeral-disk pods: no HOROVOD_PLAN_CACHE_DIR, but a rendezvous
    # KV — the plane stays live for fleet sharing (rank 0 publishes,
    # members adopt) instead of silently disabling.
    shared = _kv_world(monkeypatch)
    cfg = Config(rendezvous_addr="127.0.0.1:1")
    plan = plancache.bootstrap(cfg, _topo(rank=0, size=2), mode="tcp")
    assert plan is not None and plancache._plane.enabled
    key = plancache._KV_KEY % (plancache.SCHEMA_VERSION,
                               plancache._plane.fingerprint)
    assert key in shared  # rank 0 published (an empty plan is an answer)
    # finalize with live-tuned state republishes without a dir.
    pm = ParameterManager(1 << 23, 7.0)
    pm.frozen = True
    pm._samples_done = 5
    plancache.finalize(
        tcp_core=None, engine=types.SimpleNamespace(parameter_manager=pm))
    assert json.loads(shared[key])["tuned"]["fusion_threshold"] == 1 << 23


def test_bootstrap_kv_only_rank0_adopts_prior_instead_of_clobbering(
        monkeypatch):
    # Cross-run KV-only warm start: run 1's shutdown republished a
    # tuned plan; run 2's rank 0 (no cache dir, nothing local) must
    # adopt that prior answer — not clobber the key with empty_plan()
    # and force the fleet to re-tune every run.
    shared = _kv_world(monkeypatch)
    fp = plancache.topology_fingerprint(2, 1, "host")  # size-2 world
    key = plancache._KV_KEY % (plancache.SCHEMA_VERSION, fp)
    shared[key] = json.dumps(_plan(fp), sort_keys=True)
    cfg = Config(rendezvous_addr="127.0.0.1:1")
    plancache.bootstrap(cfg, _topo(rank=0, size=2), mode="tcp")
    assert plancache._plane.fingerprint == fp
    assert plancache._plane.source == "kv"
    assert plancache.tuned_warm_start() == (1 << 25, 3.5, True)
    assert json.loads(shared[key])["tuned"]["fusion_threshold"] == \
        1 << 25  # republished content unchanged (idempotent publish)


def test_bootstrap_member_adopts_rank0_answer_even_when_empty(
        monkeypatch, tmp_path):
    # Member has a contentful LOCAL blob but rank 0 published "no
    # plan": the member must agree with rank 0 (divergent routing
    # diverges negotiated programs), so the empty answer wins.
    fp = plancache.topology_fingerprint(2, 1, "host")  # size-2 world
    plancache.store(_plan(fp), str(tmp_path))
    shared = _kv_world(monkeypatch)
    shared[plancache._KV_KEY % (plancache.SCHEMA_VERSION, fp)] = \
        json.dumps(plancache.empty_plan(fp))
    cfg = Config(plan_cache_dir=str(tmp_path),
                 rendezvous_addr="127.0.0.1:1")
    plancache.bootstrap(cfg, _topo(rank=1, size=2), mode="tcp")
    assert plancache._plane.fingerprint == fp
    # The local blob WAS loaded (a hit) but the adopted empty answer
    # replaced it.
    assert metrics.series_sum("plan_cache_hits_total") == 1
    assert plancache.tuned_warm_start() is None  # local blob not used
    assert plancache._plane.source is None


def test_bootstrap_multihost_member_fails_loudly_on_adopt_failure(
        monkeypatch):
    # Empty KV (rank 0 never published / timed out): a multihost
    # member must not guess — default-gate routing against rank 0's
    # planned routing hangs the world, so init fails loudly instead.
    _kv_world(monkeypatch)
    cfg = Config(rendezvous_addr="127.0.0.1:1")
    with pytest.raises(RuntimeError, match="KV adoption failed"):
        plancache.bootstrap(cfg, _topo(rank=1, size=2),
                            mode="multihost")
    # The same failure on a tcp world (no routing controller) only
    # degrades to the local view.
    plancache.reset()
    plan = plancache.bootstrap(cfg, _topo(rank=1, size=2), mode="tcp")
    assert plan is not None


# -- cold-vs-warm 2-proc e2e (the CI perf-smoke scenario) ------------------

@pytest.mark.slow
def test_warm_cache_run_skips_retuning_2proc(tmp_path):
    """Run a real 2-proc tcp world twice against one shared
    HOROVOD_PLAN_CACHE_DIR: the cold run tunes and persists, the warm
    run must report ``plan_cache_hits_total`` > 0 and
    ``plan_apply_total{source="cache"}`` > 0 and skip warm-up sampling
    (asserted in-worker, where the counters live)."""
    from tests.utils.spawn import spawn_world

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "utils", "plan_warm_worker.py")
    env = {
        "HOROVOD_PLAN_CACHE_DIR": str(tmp_path),
        "HOROVOD_PLAN_CACHE": "1",
        "HOROVOD_AUTOTUNE": "1",
        # Fast native-tuner pacing: 1 warm-up cycle, 1 cycle/sample,
        # so 60 steady allreduces clear the 25-sample grid walk.
        "HVD_TPU_AUTOTUNE_WARMUP_CYCLES": "1",
        "HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE": "1",
    }
    for phase in ("cold", "warm"):
        env["PLAN_PHASE"] = phase
        # pop_env: the warm start only engages on a default-config
        # rerun (an explicit cycle-time env wins over the tuned
        # point), so neither the harness pin nor an inherited operator
        # env may reach the workers.
        results = spawn_world(worker, 2, extra_env=dict(env),
                              timeout=180,
                              pop_env=("HOROVOD_CYCLE_TIME",
                                       "HVD_TPU_CYCLE_TIME"))
        for rank, (rc, out, err) in enumerate(results):
            assert rc == 0, "%s rank %d failed:\n%s\n%s" % (
                phase, rank, out, err)
            assert ("PLAN_%s_OK" % phase.upper()) in out


# -- flash-block seeding (the folded r9 registry) --------------------------

def test_seed_tuned_blocks_roundtrip_and_malformed_skipped(caplog):
    from horovod_tpu.ops import pallas_kernels as pk
    saved = dict(pk._TUNED_BLOCKS)
    try:
        pk._TUNED_BLOCKS.clear()
        with caplog.at_level(logging.WARNING, "horovod_tpu"):
            pk.seed_tuned_blocks({"512x128": [256, 512],
                                  "notashape": [1, 2],
                                  "128x128": [0, 64],
                                  "256x128": "bogus"})
        assert pk._TUNED_BLOCKS == {(512, 128): (256, 512)}
        assert caplog.text.count("malformed tuned-block entry") == 3
        assert pk.export_tuned_blocks() == {"512x128": [256, 512]}
    finally:
        pk._TUNED_BLOCKS.clear()
        pk._TUNED_BLOCKS.update(saved)
