"""Trace-replay contract tests for the absent platforms.

pyspark/ray are not installable here, so ``horovod_tpu.spark.run`` and
``RayExecutor`` execute against recorded API surfaces
(tests/utils/fake_platforms.py) backed by REAL child processes: the
platform glue places the workers, and the user fn bootstraps a REAL
hvd TCP world through the rendezvous server that glue started — the
exact run a user would do on the real platform.  An environment with
the real dependencies runs the same framework code unchanged.
"""

import numpy as np

from tests.utils.fake_platforms import install_fake_pyspark, make_fake_ray


def _train_fn(tag):
    """The user training function: a real 2-rank hvd world over the
    platform-provided bootstrap env."""
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    out = hvd.allreduce(np.ones(3, np.float32) * (hvd.rank() + 1),
                        op=hvd.Sum, name="contract_%s" % tag)
    result = (hvd.rank(), hvd.size(), float(np.asarray(out)[0]))
    hvd.shutdown()
    return result


def test_spark_run_replay_executes_real_world(monkeypatch):
    install_fake_pyspark(monkeypatch, parallelism=2)
    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(_train_fn, args=("spark",), verbose=0)
    assert [r[0] for r in results] == [0, 1]          # rank order
    assert all(r[1] == 2 for r in results)            # world size
    np.testing.assert_allclose([r[2] for r in results], 3.0)  # 1+2


def _rank_probe():
    import os
    return int(os.environ["HOROVOD_RANK"])


def _elastic_spark_fn(marker):
    """User fn with the hvd.elastic pattern: rank 1 dies once mid-run
    (simulated hardware failure), the survivor restores its commit, the
    driver respawns the slot through the agent, and the resumed world
    finishes."""
    import os

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.ObjectState(batch=0, total=0.0)

    @elastic.run
    def train(state):
        while state.batch < 6:
            if (hvd.rank() == 1 and state.batch == 2
                    and not os.path.exists(marker)):
                open(marker, "w").write("x")
                os._exit(13)
            out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                name="sb%d" % state.batch)
            state.total += float(np.asarray(out)[0])
            state.batch += 1
            state.commit()
        return (hvd.rank(), hvd.size(), state.total)

    result = train(state)
    hvd.shutdown()
    return result


def test_spark_run_elastic_replay_executes_real_world(monkeypatch):
    # reference horovod.spark.run_elastic: Spark schedules AGENT tasks
    # (fake harness: real child processes), each registers with the
    # elastic driver, which starts the workers THROUGH the agents
    # (TaskService run/proc_poll) and collects results over the
    # rendezvous KV — no shared filesystem assumed.
    install_fake_pyspark(monkeypatch, parallelism=2)
    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run_elastic(_train_fn, args=("spark_elastic",),
                                    num_proc=2, min_np=2, verbose=0,
                                    start_timeout=60,
                                    elastic_timeout=60)
    assert [r[0] for r in results] == [0, 1]
    assert all(r[1] == 2 for r in results)
    np.testing.assert_allclose([r[2] for r in results], 3.0)


def test_spark_run_elastic_worker_failure_recovers(monkeypatch,
                                                   tmp_path):
    # Fault injection through the agent plane: a worker process dies
    # mid-training, the driver records the failure WITHOUT blacklisting
    # (failure_threshold=3 — fake world is one host), respawns the slot
    # via the agent's TaskService, and the resumed world finishes from
    # the survivor's last commit.
    install_fake_pyspark(monkeypatch, parallelism=2)
    import horovod_tpu.spark as hvd_spark
    marker = str(tmp_path / "died_once")
    results = hvd_spark.run_elastic(
        _elastic_spark_fn, args=(marker,), num_proc=2, min_np=2,
        verbose=0, start_timeout=60, elastic_timeout=60,
        failure_threshold=3)
    assert [r[0] for r in results] == [0, 1]
    assert all(r[1] == 2 for r in results)
    # 6 batches × allreduce(ones)×2 ranks = 12, restored across the
    # failure (totals synced from rank 0 at re-rendezvous).
    assert results[0][2] == 12.0
    import os
    assert os.path.exists(marker), "the injected failure never fired"


def _elastic_growing_fn():
    """Runs long enough for a late-registering agent to join; the
    HostsUpdatedInterrupt resizes the world mid-run and the remaining
    batches run at the larger size."""
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.ObjectState(batch=0, max_size=0)

    @elastic.run
    def train(state):
        while state.batch < 30:
            hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                          name="gb%d" % state.batch)
            state.max_size = max(state.max_size, hvd.size())
            state.batch += 1
            state.commit()
            if state.max_size < 2:
                time.sleep(0.5)  # give the late agent time to appear
        return (hvd.rank(), hvd.size(), state.max_size)

    result = train(state)
    hvd.shutdown()
    return result


def test_spark_run_elastic_scale_up_mid_run(monkeypatch):
    # Elastic scale-UP through the agent plane: the second agent task
    # registers ~6s late (stagger hook), discovery grows the world,
    # workers take HostsUpdatedInterrupt and re-rendezvous at size 2.
    install_fake_pyspark(monkeypatch, parallelism=2)
    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run_elastic(
        _elastic_growing_fn, num_proc=2, min_np=1, max_np=2, verbose=0,
        start_timeout=60, elastic_timeout=120,
        extra_env={"HVD_TPU_TEST_AGENT_STAGGER": "6"})
    assert [r[0] for r in results] == [0, 1]
    assert all(r[1] == 2 for r in results)      # finished at size 2
    assert all(r[2] == 2 for r in results)      # resize observed


def _failing_once_fn(marker):
    """Simulated hardware failure on the first attempt: rank 1 dies
    (process exit — dead sockets are what a real node loss looks like),
    the survivor's collective fails with HorovodInternalError, which
    surfaces from ray.get as a RayError."""
    import os

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 1 and not os.path.exists(marker):
        open(marker, "w").write("x")
        os._exit(1)
    out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                        name="retry")
    result = (hvd.rank(), hvd.size(), float(np.asarray(out)[0]))
    hvd.shutdown()
    return result


def test_elastic_ray_executor_replay_run_and_retry(monkeypatch,
                                                   tmp_path):
    # End-to-end elastic on the fake-ray actors: a clean run, then a
    # first-attempt collective failure that surfaces as RayError from
    # ray.get — the executor tears the world down, rebuilds fresh
    # actors, and the retry succeeds.
    make_fake_ray(monkeypatch)
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.ray.elastic import ElasticRayExecutor
    ex = ElasticRayExecutor(min_np=2,
                            override_discovery=FixedHosts(
                                {"127.0.0.1": 2}))
    ex.start()
    try:
        results = ex.run(_train_fn, args=("elastic_ray",))
        assert sorted(r[0] for r in results) == [0, 1]
        assert all(r[1] == 2 for r in results)
    finally:
        ex.shutdown()

    ex2 = ElasticRayExecutor(min_np=2, retries=2, cooldown_s=0.1,
                             override_discovery=FixedHosts(
                                 {"127.0.0.1": 2}))
    marker = str(tmp_path / "ray_died_once")
    try:
        results = ex2.run(_failing_once_fn, args=(marker,))
        assert sorted(r[0] for r in results) == [0, 1]
        np.testing.assert_allclose([r[2] for r in results], 2.0)
    finally:
        ex2.shutdown()
    import os
    assert os.path.exists(marker), "the injected failure never fired"


def test_mxnet_replay_real_branches_on_2rank_world():
    # A fake `mxnet` module (recorded API surface: nd.NDArray/nd.array/
    # gluon.Trainer) installed BEFORE the adapter imports, driven over
    # a real 2-process world: NDArray reconstruction and the
    # DistributedTrainer gradient averaging run the real-mxnet code
    # paths that duck-typed tests cannot reach.
    import os

    from tests.utils.spawn import assert_world_ok, spawn_world
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "utils", "mxnet_contract_worker.py")
    assert_world_ok(spawn_world(worker, 2), "MX_CONTRACT_OK")


def test_ray_executor_replay_start_run_shutdown(monkeypatch):
    make_fake_ray(monkeypatch)
    from horovod_tpu.ray import RayExecutor
    ex = RayExecutor(num_workers=2)
    ex.start()
    try:
        results = ex.run(_train_fn, args=("ray",))
        assert sorted(r[0] for r in results) == [0, 1]
        assert all(r[1] == 2 for r in results)
        np.testing.assert_allclose([r[2] for r in results], 3.0)
        # run_remote returns unresolved refs; execute_single hits rank 0.
        import ray as fake_ray
        refs = ex.run_remote(_train_fn, args=("ray2",))
        results2 = fake_ray.get(refs)
        assert sorted(r[0] for r in results2) == [0, 1]
        assert ex.execute_single(_rank_probe) == 0
    finally:
        ex.shutdown()
    assert ex._workers == []
