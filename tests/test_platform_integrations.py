"""Ray/Spark integration units (reference: test/single/test_ray.py,
test/integration/test_spark.py — here only the dependency-free parts:
rank planning, env construction, graceful gating without ray/pyspark)."""

import pytest

from horovod_tpu.ray import RayExecutor, plan_ranks
from horovod_tpu.spark import _make_mapper, default_num_proc


def test_plan_ranks_groups_by_node():
    plans = plan_ranks(["10.0.0.1", "10.0.0.1", "10.0.0.2"])
    assert [p["rank"] for p in plans] == [0, 1, 2]
    assert [p["local_rank"] for p in plans] == [0, 1, 0]
    assert [p["local_size"] for p in plans] == [2, 2, 1]
    assert [p["cross_rank"] for p in plans] == [0, 0, 1]
    assert all(p["cross_size"] == 2 for p in plans)
    assert all(p["size"] == 3 for p in plans)


def _missing(mod: str) -> bool:
    import importlib.util
    return importlib.util.find_spec(mod) is None


@pytest.mark.skipif(not _missing("ray"), reason="ray installed")
def test_ray_gated_without_dependency():
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()


@pytest.mark.skipif(not _missing("pyspark"), reason="pyspark installed")
def test_spark_gated_without_dependency():
    with pytest.raises(ImportError, match="pyspark"):
        default_num_proc()


def test_ray_run_before_start_errors():
    with pytest.raises(RuntimeError, match="not started"):
        RayExecutor(num_workers=1).run(lambda: 1)


def test_spark_mapper_is_constructible():
    # The barrier-task body (Spark ships it with cloudpickle; stdlib
    # pickle cannot round-trip closures, so only shape-check here).
    mapper = _make_mapper(lambda: 1, (), {}, 4, "1.2.3.4:5", "s",
                          {"X": "1"})
    assert callable(mapper)


def test_pack_strategy_plan():
    from horovod_tpu.ray.strategy import PackStrategy
    p = PackStrategy(num_workers=3, cpus_per_worker=2).plan()
    assert p.strategy == "PACK"
    assert p.bundles == [{"CPU": 2.0}] * 3
    assert p.worker_to_bundle == [0, 1, 2]


def test_spread_strategy_plan():
    from horovod_tpu.ray.strategy import SpreadStrategy
    p = SpreadStrategy(num_hosts=2, num_workers_per_host=3,
                       cpus_per_worker=1, gpus_per_worker=1).plan()
    assert p.strategy == "STRICT_SPREAD"
    assert p.bundles == [{"CPU": 3.0, "GPU": 3.0}] * 2
    assert p.worker_to_bundle == [0, 0, 0, 1, 1, 1]
    assert p.num_workers == 6


def test_strategy_validation():
    import pytest
    from horovod_tpu.ray.strategy import PackStrategy, SpreadStrategy
    with pytest.raises(ValueError):
        PackStrategy(0)
    with pytest.raises(ValueError):
        SpreadStrategy(1, 0)


def test_ray_host_discovery_slot_math():
    from horovod_tpu.ray.elastic import RayHostDiscovery

    class FakeDiscovery(RayHostDiscovery):
        def _nodes(self):
            return [
                {"Alive": True, "NodeManagerAddress": "10.0.0.1",
                 "Resources": {"CPU": 8.0, "GPU": 2.0}},
                {"Alive": True, "NodeManagerAddress": "10.0.0.2",
                 "Resources": {"CPU": 3.0}},
                {"Alive": False, "NodeManagerAddress": "10.0.0.3",
                 "Resources": {"CPU": 64.0}},
            ]

    cpu = FakeDiscovery(use_gpu=False, cpus_per_slot=2)
    assert cpu.find_available_hosts_and_slots() == {
        "10.0.0.1": 4, "10.0.0.2": 1}
    gpu = FakeDiscovery(use_gpu=True, gpus_per_slot=1)
    assert gpu.find_available_hosts_and_slots() == {"10.0.0.1": 2}


def test_elastic_ray_executor_min_np_guard():
    import pytest
    from horovod_tpu.ray.elastic import (ElasticRayExecutor,
                                         RayHostDiscovery)

    class Empty(RayHostDiscovery):
        def _nodes(self):
            return []

    ex = ElasticRayExecutor(min_np=2, override_discovery=Empty())
    with pytest.raises(RuntimeError):
        ex._current_np()


def test_ray_executor_requires_worker_spec():
    import pytest
    from horovod_tpu.ray import RayExecutor
    with pytest.raises(ValueError):
        RayExecutor()


def test_spark_agent_registry_compaction_and_ping_tolerance():
    # Fault injection on the spark-elastic agent plane: a dead agent is
    # dropped only after consecutive ping failures, and per-host lists
    # compact so (host, i) keeps resolving to the i-th LIVE agent —
    # the slot-renumbering contract ordered_slots relies on.
    from horovod_tpu.runner.services import MessageServer
    from horovod_tpu.runner import util
    from horovod_tpu.spark.elastic import AgentDiscovery, _AgentRegistry

    secret = util.make_secret()
    servers = [MessageServer(lambda req: {"ok": True}, secret)
               for _ in range(3)]
    ports = [s.start() for s in servers]
    reg = _AgentRegistry()
    for p in ports:
        reg.register("127.0.0.1", p)
    disc = AgentDiscovery(reg, secret)
    assert disc.find_available_hosts_and_slots() == {"127.0.0.1": 3}

    # Kill the middle agent: host count must NOT drop on the first
    # failed ping (transient tolerance)...
    servers[1].stop()
    assert disc.find_available_hosts_and_slots() == {"127.0.0.1": 3}
    assert disc.find_available_hosts_and_slots() == {"127.0.0.1": 3}
    # ...but the third consecutive failure drops it and compacts.
    assert disc.find_available_hosts_and_slots() == {"127.0.0.1": 2}
    assert reg.addr(("127.0.0.1", 0)) == ("127.0.0.1", ports[0])
    assert reg.addr(("127.0.0.1", 1)) == ("127.0.0.1", ports[2])
    assert reg.addr(("127.0.0.1", 2)) is None
    # A ping that succeeds again resets the failure counter: seed a
    # live agent with 2 prior blips — the successful round must clear
    # them (otherwise blips spread over time would accumulate to a
    # drop).
    live = ("127.0.0.1", ports[0])
    disc._ping_failures[live] = 2
    assert disc.find_available_hosts_and_slots() == {"127.0.0.1": 2}
    assert live not in disc._ping_failures
    for s in (servers[0], servers[2]):
        s.stop()


def test_elastic_ray_retry_budget(monkeypatch):
    from horovod_tpu.ops.engine import HorovodInternalError
    from horovod_tpu.ray.elastic import (ElasticRayExecutor,
                                         RayHostDiscovery)

    class One(RayHostDiscovery):
        def _nodes(self):
            return [{"Alive": True, "NodeManagerAddress": "h",
                     "Resources": {"CPU": 1.0}}]

    ex = ElasticRayExecutor(min_np=1, retries=2, cooldown_s=0,
                            override_discovery=One())
    attempts = []

    class FakeExecutor:
        def run(self, fn, args=(), kwargs=None):
            attempts.append(1)
            raise HorovodInternalError("boom")

        def shutdown(self):
            pass

    monkeypatch.setattr(ElasticRayExecutor, "start",
                        lambda self: setattr(self, "_executor",
                                             FakeExecutor()))
    import pytest
    with pytest.raises(HorovodInternalError):
        ex.run(lambda: None)
    # initial attempt + 2 retries
    assert len(attempts) == 3
