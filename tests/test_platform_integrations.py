"""Ray/Spark integration units (reference: test/single/test_ray.py,
test/integration/test_spark.py — here only the dependency-free parts:
rank planning, env construction, graceful gating without ray/pyspark)."""

import pytest

from horovod_tpu.ray import RayExecutor, plan_ranks
from horovod_tpu.spark import _make_mapper, default_num_proc


def test_plan_ranks_groups_by_node():
    plans = plan_ranks(["10.0.0.1", "10.0.0.1", "10.0.0.2"])
    assert [p["rank"] for p in plans] == [0, 1, 2]
    assert [p["local_rank"] for p in plans] == [0, 1, 0]
    assert [p["local_size"] for p in plans] == [2, 2, 1]
    assert [p["cross_rank"] for p in plans] == [0, 0, 1]
    assert all(p["cross_size"] == 2 for p in plans)
    assert all(p["size"] == 3 for p in plans)


def _missing(mod: str) -> bool:
    import importlib.util
    return importlib.util.find_spec(mod) is None


@pytest.mark.skipif(not _missing("ray"), reason="ray installed")
def test_ray_gated_without_dependency():
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()


@pytest.mark.skipif(not _missing("pyspark"), reason="pyspark installed")
def test_spark_gated_without_dependency():
    with pytest.raises(ImportError, match="pyspark"):
        default_num_proc()


def test_ray_run_before_start_errors():
    with pytest.raises(RuntimeError, match="not started"):
        RayExecutor(num_workers=1).run(lambda: 1)


def test_spark_mapper_is_constructible():
    # The barrier-task body (Spark ships it with cloudpickle; stdlib
    # pickle cannot round-trip closures, so only shape-check here).
    mapper = _make_mapper(lambda: 1, (), {}, 4, "1.2.3.4:5", "s",
                          {"X": "1"})
    assert callable(mapper)
