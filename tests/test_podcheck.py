"""Schema smoke for the pod-day readiness artifact.

The real podcheck number (allreduce efficiency >= 0.90 of ICI link
bandwidth, BASELINE.md) needs a multi-chip slice; this test validates
that ``benchmarks/podcheck.py --cpu-smoke`` produces the one-artifact
JSON the first hardware session will ship — so pod day starts with a
known-good entry point instead of improvisation (VERDICT r4 Next #7).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_podcheck_smoke_artifact_schema(tmp_path):
    out = tmp_path / "podcheck.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "podcheck.py"),
         "--cpu-smoke", "--skip-autotune", "--out", str(out)],
        cwd=REPO, timeout=600,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert proc.returncode == 0, proc.stdout.decode()[-2000:]
    art = json.loads(out.read_text())
    # BENCH_r*.json schema head.
    for key in ("metric", "value", "unit", "vs_baseline", "target",
                "pass", "sections", "smoke", "link_gbps"):
        assert key in art, "missing %r in artifact" % key
    assert art["metric"] == "allreduce_efficiency_vs_link"
    assert art["target"] == 0.90
    assert art["smoke"] is True
    by_name = {s["name"]: s for s in art["sections"]}
    assert set(by_name) == {"allreduce_bw", "scaling_efficiency",
                            "bench", "autotune_ab",
                            "hier_allgather_ab"}
    # The bandwidth section must have run and carried the summary line
    # the headline is computed from.
    bw = by_name["allreduce_bw"]
    assert bw["ok"], bw
    assert any(r.get("metric") == "allreduce_bus_bandwidth_peak"
               for r in bw["records"]), bw["records"]
    assert by_name["scaling_efficiency"]["ok"]
    # bench needs the real chip; smoke marks it skipped, not failed.
    assert by_name["bench"]["skipped"] is True
    assert by_name["autotune_ab"]["skipped"] is True  # --skip-autotune
    # The non-allreduce pod A/B (hier legs off vs on) must have run
    # both arms and produced the eager allgather records.
    hier = by_name["hier_allgather_ab"]
    assert hier["ok"], hier
    assert len(hier["arms"]) == 2
    for arm in hier["arms"]:
        assert any(r.get("metric") == "allgather_bus_bandwidth_peak"
                   for r in arm["records"]), arm
