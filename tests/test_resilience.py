"""Self-healing collective data plane units (ISSUE 18): deadline
scaling, transient-vs-fatal leg classification, bounded retry under
injected flakes, CRC retry-then-escalate, streak-driven demotion /
re-promotion, and the SPMD-uniform rank-0 KV verdict protocol."""

import logging
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import faultline, metrics, resilience
from horovod_tpu.utils import plancache


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("HVD_TPU_FAULT", "HOROVOD_COLLECTIVE_TIMEOUT_SECS",
                "HOROVOD_COLLECTIVE_TIMEOUT_PER_GIB",
                "HOROVOD_LEG_MAX_RETRIES", "HOROVOD_LEG_RETRY_BACKOFF",
                "HOROVOD_LEG_DEMOTE_THRESHOLD",
                "HOROVOD_LEG_REPROBE_SECS",
                "HOROVOD_DATA_PLANE_DEGRADE", "HOROVOD_WIRE_INTEGRITY",
                "HOROVOD_DATA_PLANE_CHECK_EVERY"):
        monkeypatch.delenv(var, raising=False)
    # Fast retries for every test that exhausts a budget.
    monkeypatch.setenv("HOROVOD_LEG_RETRY_BACKOFF", "0.001")
    faultline.reset()
    metrics.reset()
    resilience.reset()
    plancache.reset()
    yield
    faultline.reset()
    metrics.reset()
    resilience.reset()
    plancache.reset()


# -- deadlines --------------------------------------------------------------

def test_deadline_off_by_default():
    assert resilience.collective_timeout_secs() == 0.0
    assert resilience.collective_deadline(1 << 30) == 0.0


def test_deadline_scales_with_size_class(monkeypatch):
    monkeypatch.setenv("HOROVOD_COLLECTIVE_TIMEOUT_SECS", "10")
    monkeypatch.setenv("HOROVOD_COLLECTIVE_TIMEOUT_PER_GIB", "30")
    assert resilience.collective_deadline(0) == 10.0
    assert resilience.collective_deadline(1 << 30) == 40.0
    assert resilience.collective_deadline(1 << 29) == 25.0


def test_group_deadline_is_thread_local():
    resilience.set_group_deadline(123.0)
    seen = []

    def other():
        seen.append(resilience.group_deadline())

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen == [None]
    assert resilience.group_deadline() == 123.0
    resilience.set_group_deadline(None)


# -- classification ---------------------------------------------------------

@pytest.mark.parametrize("exc,transient", [
    (resilience.LegTransportError("x"), True),
    (ConnectionResetError("peer reset"), True),
    (TimeoutError("t"), True),
    (RuntimeError("UNAVAILABLE: connection reset by peer"), True),
    (RuntimeError("DEADLINE_EXCEEDED while awaiting DCN send"), True),
    (resilience.WireIntegrityError("crc"), False),
    (ValueError("bad shape"), False),
    (TypeError("bad dtype"), False),
    (RuntimeError("INVALID_ARGUMENT: dimension mismatch"), False),
])
def test_is_transient_leg(exc, transient):
    assert resilience.is_transient_leg(exc) is transient


def test_failure_reason_buckets():
    from horovod_tpu.ops.engine import CollectiveDeadlineExceeded
    assert resilience.failure_reason(
        CollectiveDeadlineExceeded("collective deadline exceeded: g"))\
        == "deadline"
    assert resilience.failure_reason(
        resilience.WireIntegrityError("crc")) == "corrupt"
    assert resilience.failure_reason(
        resilience.LegTransportError("drop")) == "transport"
    assert resilience.failure_reason(
        RuntimeError("connection refused")) == "transport"
    assert resilience.failure_reason(ValueError("shape")) == "error"


# -- the leg guard ----------------------------------------------------------

def _arm(spec, monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", spec)
    faultline.reset()


def test_bounded_flake_is_absorbed(monkeypatch):
    # Two injected drops, default budget of two retries: the leg
    # succeeds, the retries are counted, and the streak stays clean.
    _arm("mh.leg.drop:drop@times=2", monkeypatch)
    calls = []
    out = resilience.run_hier_leg(
        "allreduce", "20", lambda: calls.append(1) or "ok")
    assert out == "ok"
    assert len(calls) == 1  # the first two attempts dropped pre-stage
    assert metrics.series_sum("mh_leg_retries_total",
                              op="allreduce") == 2
    assert resilience._state.streak == {}


def test_retry_exhaustion_raises_leg_degraded(monkeypatch):
    _arm("mh.leg.drop:drop", monkeypatch)  # unbounded
    with pytest.raises(resilience.LegDegraded) as ei:
        resilience.run_hier_leg("allreduce", "20", lambda: "never")
    assert ei.value.op == "allreduce"
    assert ei.value.size_class == "20"
    assert isinstance(ei.value.cause, resilience.LegTransportError)
    # 1 first attempt + 2 retries failed -> one exhaustion streak.
    assert resilience._state.streak == {("allreduce", "20"): 1}
    assert metrics.series_sum("mh_leg_retries_total",
                              op="allreduce") == 2


def test_degrade_disabled_escalates_transport_error(monkeypatch):
    monkeypatch.setenv("HOROVOD_DATA_PLANE_DEGRADE", "0")
    _arm("mh.leg.drop:drop", monkeypatch)
    with pytest.raises(resilience.LegTransportError):
        resilience.run_hier_leg("allreduce", "20", lambda: "never")


def test_fatal_error_never_retries(monkeypatch):
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("dimension mismatch")

    with pytest.raises(ValueError):
        resilience.run_hier_leg("allreduce", "20", boom)
    assert len(calls) == 1
    assert metrics.series_sum("mh_leg_retries_total") == 0


def test_group_deadline_bounds_retries(monkeypatch):
    # Plenty of retry budget, but the group deadline has already
    # passed: the first transient failure exhausts immediately.
    monkeypatch.setenv("HOROVOD_LEG_MAX_RETRIES", "50")
    _arm("mh.leg.drop:drop", monkeypatch)
    resilience.set_group_deadline(time.monotonic() - 1.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(resilience.LegDegraded):
            resilience.run_hier_leg("allreduce", "20", lambda: "never")
        assert time.monotonic() - t0 < 1.0
    finally:
        resilience.set_group_deadline(None)


def test_success_resets_streak(monkeypatch):
    _arm("mh.leg.drop:drop@times=3", monkeypatch)  # 1 attempt + 2
    with pytest.raises(resilience.LegDegraded):
        resilience.run_hier_leg("allreduce", "20", lambda: "never")
    assert resilience._state.streak == {("allreduce", "20"): 1}
    resilience.run_hier_leg("allreduce", "20", lambda: "ok")
    assert resilience._state.streak == {}


# -- wire integrity ---------------------------------------------------------

def test_crc_mismatch_retries_once_then_succeeds(monkeypatch):
    _arm("mh.leg.corrupt:drop@times=1", monkeypatch)
    payload = np.arange(16, dtype=np.int8)
    calls = []
    out = resilience.run_hier_leg(
        "allreduce", "20", lambda: calls.append(1) or "ok",
        payloads=(payload,), quantized=True)
    assert out == "ok"
    assert len(calls) == 2  # corrupted attempt + the clean re-stage
    assert metrics.series_sum("mh_leg_retries_total") == 1
    assert resilience._state.streak == {}


def test_crc_mismatch_escalates_after_one_retry(monkeypatch):
    _arm("mh.leg.corrupt:drop", monkeypatch)  # persistent corruption
    payload = np.arange(16, dtype=np.int8)
    with pytest.raises(resilience.WireIntegrityError):
        resilience.run_hier_leg("allreduce", "20", lambda: "ok",
                                payloads=(payload,), quantized=True)
    assert metrics.series_sum("mh_leg_retries_total") == 1
    assert resilience._state.streak == {("allreduce", "20"): 1}


def test_crc_detects_real_payload_mutation():
    # No injection: the staged payload actually changing across the
    # dispatch window is the real defect the checksum exists to catch.
    payload = np.arange(16, dtype=np.int8)

    def mutate():
        payload[0] += 1
        return "ok"

    with pytest.raises(resilience.WireIntegrityError):
        resilience.run_hier_leg("allreduce", "20", mutate,
                                payloads=(payload,), quantized=True)


def test_crc_skipped_when_integrity_disabled(monkeypatch):
    monkeypatch.setenv("HOROVOD_WIRE_INTEGRITY", "0")
    _arm("mh.leg.corrupt:drop", monkeypatch)
    payload = np.arange(16, dtype=np.int8)
    assert resilience.run_hier_leg(
        "allreduce", "20", lambda: "ok",
        payloads=(payload,), quantized=True) == "ok"


def test_wire_checksum_is_order_and_content_sensitive():
    a = np.arange(8, dtype=np.float32)
    b = np.arange(8, dtype=np.float32) * 2
    assert resilience.wire_checksum(a, b) != \
        resilience.wire_checksum(b, a)
    c = a.copy()
    assert resilience.wire_checksum(a) == resilience.wire_checksum(c)
    c[3] = -1
    assert resilience.wire_checksum(a) != resilience.wire_checksum(c)


# -- demotion / re-promotion (local world) ----------------------------------

def _exhaust(n, monkeypatch, op="allreduce", cls="20"):
    _arm("mh.leg.drop:drop", monkeypatch)
    for _ in range(n):
        with pytest.raises(resilience.LegDegraded):
            resilience.run_hier_leg(op, cls, lambda: "never")
    monkeypatch.delenv("HVD_TPU_FAULT")
    faultline.reset()


def test_local_world_demotes_and_reprobes(monkeypatch):
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "3")
    _exhaust(3, monkeypatch)
    verdict = resilience.check_degraded_routes()
    assert verdict == {"action": "demote", "op": "allreduce",
                       "size_class": "20", "streak": 3, "apply_at": 1}
    assert resilience.demoted("allreduce", "20")
    assert metrics.series_sum("mh_degraded_routes",
                              op="allreduce") == 1
    # Below-threshold streaks never demote.
    assert resilience.check_degraded_routes() is None
    # Re-promotion: age the demotion past the probe window.
    monkeypatch.setenv("HOROVOD_LEG_REPROBE_SECS", "0.01")
    with resilience._state.lock:
        resilience._state.demoted[("allreduce", "20")] -= 1.0
    verdict = resilience.check_degraded_routes()
    assert verdict["action"] == "promote"
    assert not resilience.demoted("allreduce", "20")
    assert metrics.series_sum("mh_degraded_routes",
                              op="allreduce") == 0


def test_reprobe_zero_means_permanent_demotion(monkeypatch):
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "1")
    monkeypatch.setenv("HOROVOD_LEG_REPROBE_SECS", "0")
    _exhaust(1, monkeypatch)
    assert resilience.check_degraded_routes()["action"] == "demote"
    with resilience._state.lock:
        resilience._state.demoted[("allreduce", "20")] -= 3600.0
    assert resilience.check_degraded_routes() is None
    assert resilience.demoted("allreduce", "20")


def test_demotion_pins_controller_flat(monkeypatch):
    # The plan plane and the resilience override must agree: demotion
    # pins (op, cls) flat in the controller, promotion drops the pin.
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "1")
    plane = plancache.world_plane()
    plane.controller = plancache.PlanController(
        "fp-test", {"schema": plancache.SCHEMA_VERSION,
                    "fingerprint": "fp-test", "plans": {}},
        "cache", "none", hier_available=True, env_pinned=False)
    _exhaust(1, monkeypatch)
    assert resilience.check_degraded_routes()["action"] == "demote"
    assert plane.controller.route("allreduce", "20", True) == \
        (False, False)
    monkeypatch.setenv("HOROVOD_LEG_REPROBE_SECS", "0.01")
    with resilience._state.lock:
        resilience._state.demoted[("allreduce", "20")] -= 1.0
    assert resilience.check_degraded_routes()["action"] == "promote"
    assert plane.controller.route("allreduce", "20", True) == \
        (True, True)


def test_degrade_disabled_skips_check(monkeypatch):
    monkeypatch.setenv("HOROVOD_DATA_PLANE_DEGRADE", "off")
    assert resilience.check_degraded_routes() is None


# -- SPMD-uniform verdict adoption (fake KV) --------------------------------

class _FakeKV:
    def __init__(self):
        self.store = {}

    def put_json(self, key, obj):
        import json
        self.store[key] = json.dumps(obj)

    def get_json(self, key):
        import json
        v = self.store.get(key)
        return json.loads(v) if v is not None else None


def test_spmd_members_adopt_rank0_verdict(monkeypatch):
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "1")
    kv = _FakeKV()
    plane = plancache.world_plane()
    plane.kv, plane.size, plane.fingerprint = kv, 2, "fp-spmd"
    # rank 0: a tripped streak publishes the demote verdict.
    plane.rank = 0
    _exhaust(1, monkeypatch)
    assert resilience.check_degraded_routes()["action"] == "demote"
    assert resilience.demoted("allreduce", "20")
    # member (fresh process state, same world identity): adopts the
    # SAME verdict at ITS check #1 without any local failure evidence.
    resilience.reset()
    plane.rank = 1
    assert not resilience.demoted("allreduce", "20")
    verdict = resilience.check_degraded_routes(timeout=1.0)
    assert verdict == {"action": "demote", "op": "allreduce",
                       "size_class": "20", "streak": 1, "apply_at": 1}
    assert resilience.demoted("allreduce", "20")
    # Next member check: the verdict is applied exactly once.
    kv.put_json(resilience._DEGRADED_KEY
                % (resilience.SCHEMA_VERSION, "fp-spmd"),
                {"seq": 2, "routes": [verdict]})
    assert resilience.check_degraded_routes(timeout=1.0) is None


def test_spmd_member_without_record_raises(monkeypatch):
    plane = plancache.world_plane()
    plane.kv, plane.size, plane.rank = _FakeKV(), 2, 1
    plane.fingerprint = "fp-spmd"
    with pytest.raises(RuntimeError, match="never published"):
        resilience.check_degraded_routes(timeout=0.15)


def test_spmd_no_kv_observes_nothing(monkeypatch, caplog):
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "1")
    plane = plancache.world_plane()
    plane.size, plane.rank, plane.kv = 2, 0, None
    _exhaust(1, monkeypatch)
    with caplog.at_level(logging.WARNING, "horovod_tpu.resilience"):
        assert resilience.check_degraded_routes() is None
        assert resilience.check_degraded_routes() is None
    assert caplog.text.count("no rendezvous KV") == 1  # warned once
    assert not resilience.demoted("allreduce", "20")


# -- commit-cadence hook ----------------------------------------------------

def test_commit_hook_off_by_default(monkeypatch):
    calls = []
    monkeypatch.setattr(resilience, "check_degraded_routes",
                        lambda timeout=60.0: calls.append(1))
    for _ in range(5):
        resilience.maybe_check_at_commit()
    assert calls == []


def test_commit_hook_cadence(monkeypatch):
    monkeypatch.setenv("HOROVOD_DATA_PLANE_CHECK_EVERY", "3")
    calls = []
    monkeypatch.setattr(resilience, "check_degraded_routes",
                        lambda timeout=60.0: calls.append(1) or None)
    for _ in range(7):
        resilience.maybe_check_at_commit()
    assert len(calls) == 2  # commits 3 and 6


# -- attribution ------------------------------------------------------------

def test_describe_reports_knobs_and_evidence(monkeypatch):
    monkeypatch.setenv("HOROVOD_COLLECTIVE_TIMEOUT_SECS", "12")
    monkeypatch.setenv("HOROVOD_LEG_DEMOTE_THRESHOLD", "1")
    _exhaust(1, monkeypatch)
    resilience.check_degraded_routes()
    metrics.counter("mh_collective_failures_total", op="allreduce",
                    reason="transport").inc()
    d = resilience.describe()
    assert d["deadline_secs"] == 12.0
    assert d["demoted_routes"] == [
        {"op": "allreduce", "size_class": "20"}]
    assert d["leg_retries_total"] == 2.0
    assert d["failures_by_reason"] == {"transport": 1.0}
