"""Launcher tests: arg/host parsing, env construction, services, safe
exec, rendezvous auth, and a real static end-to-end run on localhost
(reference: test/single/test_run.py + test/integration/test_static_run.py)."""

import os

from tests.utils.spawn import scaled_timeout
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu.runner import util
from horovod_tpu.runner.launch import (build_common_env, gloo_run,
                                       parse_args, worker_env,
                                       _slot_assignments)
from horovod_tpu.runner.http_client import RendezvousClient
from horovod_tpu.runner.http_server import RendezvousServer
from horovod_tpu.runner.services import DriverService, TaskService
from horovod_tpu.runner import safe_shell_exec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_hosts():
    hosts = util.parse_hosts("a:4,b:2,c")
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("a", 4), ("b", 2), ("c", 1)]
    assert util.total_slots(hosts) == 7
    with pytest.raises(ValueError):
        util.parse_hosts("")


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("# comment\nnode1 slots=4\nnode2:2\n")
    hosts = util.parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("node1", 4), ("node2", 2)]


def test_slot_assignments():
    hosts = util.parse_hosts("a:2,b:2")
    slots, cross = _slot_assignments(hosts, 3)
    assert cross == 2
    assert [(s[0], s[1], s[2]) for s in slots] == [
        ("a", 0, 0), ("a", 1, 1), ("b", 2, 0)]
    with pytest.raises(ValueError):
        _slot_assignments(hosts, 9)


def test_parse_args_and_env():
    args = parse_args(["-np", "2", "--fusion-threshold-mb", "8",
                       "--cycle-time-ms", "2", "--autotune",
                       "--timeline-filename", "/tmp/tl",
                       "python", "train.py"])
    assert args.np == 2 and args.command == ["python", "train.py"]
    env = build_common_env(args, {})
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "2.0"
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert env["HOROVOD_TIMELINE"] == "/tmp/tl"
    wenv = worker_env(env, 1, 2, 1, 2, 0, 1, "127.0.0.1:9", "s", 29600)
    assert wenv["HOROVOD_RANK"] == "1"
    assert wenv["HOROVOD_CONTROLLER"] == "tcp"


def test_package_import_is_framework_free(tmp_path):
    # The lazy top-level namespace (PEP 562, reference: slim
    # horovod/__init__.py) must not pull jax: launcher-only hosts run
    # `python -m horovod_tpu.runner` framework-free.  This box's
    # sitecustomize preloads jax into every interpreter, so simulate a
    # jax-less host with a raising stub on PYTHONPATH (which also
    # bypasses that sitecustomize).
    (tmp_path / "jax.py").write_text(
        "raise ImportError('no jax on this host (simulated)')\n")
    code = ("import horovod_tpu, horovod_tpu.runner; "
            "assert horovod_tpu.__version__; "
            "from horovod_tpu.runner.launch import check_build; "
            "import io; buf = io.StringIO(); check_build(out=buf); "
            "assert '[ ] JAX' in buf.getvalue(); "
            "print('LAZY_OK')")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "%s%s%s" % (tmp_path, os.pathsep, REPO)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=scaled_timeout(120),
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LAZY_OK" in proc.stdout


def test_check_build_matrix():
    # Reference `horovodrun --check-build`: feature matrix prints and
    # exits 0 without a worker command.
    import io
    from horovod_tpu.runner.launch import check_build, parse_args
    args = parse_args(["--check-build"])
    assert args.check_build and args.command == []
    buf = io.StringIO()
    assert check_build(out=buf) == 0
    text = buf.getvalue()
    assert "Available Frameworks" in text
    assert "[X] JAX" in text
    assert "Available Controllers" in text
    assert "Available Tensor Operations" in text
    assert "[ ] NCCL" in text  # absent by design, honestly reported


def test_cli_backend_flags():
    from horovod_tpu.runner.launch import parse_args
    args = parse_args(["--gloo", "-np", "2", "python", "x.py"])
    assert args.gloo and args.np == 2
    with pytest.raises(SystemExit):
        parse_args(["--mpi", "-np", "2", "python", "x.py"])


def test_parse_args_requires_command():
    with pytest.raises(SystemExit):
        parse_args(["-np", "2"])


def test_safe_shell_exec_streams_and_kills():
    lines = []
    rc = safe_shell_exec.execute(
        [sys.executable, "-c", "print('hello'); print('world')"],
        stdout_sink=lines.append)
    assert rc == 0
    assert "".join(lines) == "hello\nworld\n"
    # Termination of a hanging tree.
    mp = safe_shell_exec.ManagedProcess(
        [sys.executable, "-c", "import time; time.sleep(600)"])
    t0 = time.monotonic()
    mp.terminate()
    assert mp.proc.poll() is not None
    assert time.monotonic() - t0 < safe_shell_exec.\
        GRACEFUL_TERMINATION_TIME_S + 2


def test_rpc_transient_classification():
    # Transient: the peer (or the path to it) is momentarily gone.
    import urllib.error
    from horovod_tpu.runner.http_client import is_transient
    assert is_transient(ConnectionRefusedError("refused"))
    assert is_transient(ConnectionResetError("reset"))
    assert is_transient(TimeoutError("slow"))
    assert is_transient(
        urllib.error.URLError(ConnectionRefusedError("refused")))
    assert is_transient(
        urllib.error.HTTPError("u", 500, "handler died", {}, None))
    assert is_transient(
        urllib.error.HTTPError("u", 503, "overloaded", {}, None))
    # Local resource pressure (fd / ephemeral-port exhaustion from
    # per-poll connections) passes as the kernel recycles — retry.
    import errno
    assert is_transient(OSError(errno.EMFILE, "too many open files"))
    assert is_transient(OSError(errno.EADDRNOTAVAIL, "no free ports"))
    # Fatal: the server answered, and the answer is "no".
    assert not is_transient(
        urllib.error.HTTPError("u", 403, "bad secret", {}, None))
    assert not is_transient(
        urllib.error.HTTPError("u", 400, "bad request", {}, None))
    assert not is_transient(PermissionError("bad MAC"))
    assert not is_transient(ValueError("not an rpc failure at all"))


def test_request_with_retry_absorbs_transient_failures():
    from horovod_tpu.runner.http_client import request_with_retry
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("flake")
        return "ok"

    assert request_with_retry(flaky, backoff=0.01) == "ok"
    assert len(calls) == 3


def test_request_with_retry_never_retries_fatal():
    from horovod_tpu.runner.http_client import request_with_retry
    calls = []

    def fatal():
        calls.append(1)
        raise PermissionError("auth rejection")

    with pytest.raises(PermissionError):
        request_with_retry(fatal, backoff=0.01)
    assert len(calls) == 1


def test_request_with_retry_exhaustion_raises_last_error():
    from horovod_tpu.runner.http_client import request_with_retry
    calls = []

    def always_down():
        calls.append(1)
        raise ConnectionRefusedError("down for good")

    with pytest.raises(ConnectionRefusedError):
        request_with_retry(always_down, max_retries=2, backoff=0.01)
    assert len(calls) == 3  # first attempt + 2 retries


def test_request_with_retry_respects_deadline():
    from horovod_tpu.runner.http_client import request_with_retry

    def always_down():
        raise ConnectionRefusedError("down")

    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError):
        request_with_retry(always_down, max_retries=1000,
                           backoff=0.05, deadline=0.3)
    assert time.monotonic() - t0 < 5.0


class _FlakyStore(dict):
    """KV store whose first N writes raise (server-side handler crash
    → the server answers 500, which the client must retry)."""

    def __init__(self, failures: int):
        super().__init__()
        self.failures = failures

    def __setitem__(self, key, value):
        if self.failures > 0:
            self.failures -= 1
            raise RuntimeError("injected store failure")
        dict.__setitem__(self, key, value)


def test_rendezvous_5xx_is_retried(monkeypatch):
    # A crashing PUT handler answers 500 (not a torn connection); the
    # client's retry layer absorbs it and the write lands.
    monkeypatch.setenv("HOROVOD_RPC_RETRY_BACKOFF", "0.01")
    server = RendezvousServer(secret="s")
    port = server.start()
    try:
        server._httpd.store = _FlakyStore(failures=2)
        client = RendezvousClient("127.0.0.1:%d" % port, secret="s")
        client.put("addr/0", "1.2.3.4:5")
        assert client.get("addr/0") == "1.2.3.4:5"
    finally:
        server.stop()


def test_rendezvous_auth_403_fails_immediately(monkeypatch):
    # An HMAC rejection is fatal: no backoff sleep may happen on the
    # way to the raise (retrying an auth failure hammers the server
    # with requests it already refused).
    import urllib.error

    def no_sleep(_secs):
        raise AssertionError("403 must not be retried")

    server = RendezvousServer(secret="right")
    port = server.start()
    try:
        monkeypatch.setattr(time, "sleep", no_sleep)
        bad = RendezvousClient("127.0.0.1:%d" % port, secret="wrong")
        with pytest.raises(urllib.error.HTTPError) as err:
            bad.put("addr/0", "x")
        assert err.value.code == 403
    finally:
        monkeypatch.undo()
        server.stop()


def test_rpc_drop_and_recover_end_to_end():
    """Self-healing RPC plane, certified by injection: every process's
    first two control-plane RPC attempts fail with a synthetic
    connection reset (HVD_TPU_FAULT runner.rpc.request, @times=2), and
    the run must still complete — the retry/backoff layer absorbs the
    transient window."""
    script = (
        "import horovod_tpu as hvd, numpy as np\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,"
        " name='t')\n"
        "np.testing.assert_allclose(np.asarray(out), 2.0)\n"
        "print('RANK_OK', hvd.rank())\n"
        "hvd.shutdown()\n")
    env = _worker_env()
    env["HVD_TPU_FAULT"] = "runner.rpc.request:drop@times=2"
    env["HOROVOD_RPC_RETRY_BACKOFF"] = "0.05"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=scaled_timeout(180),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert "RANK_OK %d" % r in proc.stdout


def test_rpc_retry_exhaustion_fails_loudly():
    """The escalation boundary: with the drop armed permanently, the
    bounded retry budget exhausts and the run FAILS (non-zero rc,
    bounded wall time) — transient-fault absorption never downgrades a
    persistent fault into a hang."""
    script = (
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "print('UNREACHED')\n")
    env = _worker_env()
    env["HVD_TPU_FAULT"] = "runner.rpc.request:drop"
    env["HOROVOD_RPC_MAX_RETRIES"] = "2"
    env["HOROVOD_RPC_RETRY_BACKOFF"] = "0.05"
    env["HOROVOD_RPC_DEADLINE"] = "5"
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=scaled_timeout(120),
        env=env, cwd=REPO)
    assert proc.returncode != 0
    assert "UNREACHED" not in proc.stdout
    assert "injected transient RPC failure" in proc.stdout + proc.stderr
    assert time.monotonic() - t0 < scaled_timeout(90)


def test_rendezvous_kv_and_auth():
    server = RendezvousServer(secret="topsecret")
    port = server.start()
    try:
        good = RendezvousClient("127.0.0.1:%d" % port, secret="topsecret")
        good.put("addr/0", "1.2.3.4:5")
        assert good.get("addr/0") == "1.2.3.4:5"
        assert good.get("missing") is None
        bad = RendezvousClient("127.0.0.1:%d" % port, secret="wrong")
        with pytest.raises(Exception):
            bad.put("addr/1", "x")
        assert good.get("addr/1") is None  # unauthorized write rejected
        good.delete("addr/0")
        assert good.get("addr/0") is None
    finally:
        server.stop()


def test_driver_task_services():
    task = TaskService(index=3, secret="s3cr3t")
    port = task.start()
    try:
        driver = DriverService(secret="s3cr3t")
        info = driver.probe(("127.0.0.1", port))
        assert info["index"] == 3
        assert "127.0.0.1" in info["addresses"]
        got = []
        task.on_notify(got.append)
        driver.notify(("127.0.0.1", port), {"hosts": ["a:1"]})
        assert got == [{"hosts": ["a:1"]}]
        # Wrong secret is rejected (connection dropped / no valid reply).
        bad = DriverService(secret="wrong")
        with pytest.raises(Exception):
            bad.probe(("127.0.0.1", port), timeout=2.0)
    finally:
        task.stop()


def test_task_service_proc_poll_distinguishes_no_proc():
    # An agent with NO process (restarted, lost state) must not read as
    # "running" forever: proc_poll carries has_proc so the elastic
    # driver's _AgentProc treats it as a failed spawn and retries.
    from horovod_tpu.runner.services import send_message
    from horovod_tpu.spark.elastic import _AgentProc
    task = TaskService(index=0, secret="k")
    port = task.start()
    try:
        resp = send_message(("127.0.0.1", port), "k",
                            {"kind": "proc_poll"}, timeout=5.0)
        assert resp == {"rc": None, "has_proc": False}
        proxy = _AgentProc(("127.0.0.1", port), "k")
        assert proxy.poll() == 1  # no-proc reads as failed, not alive
        # A real (running) proc reads as alive, then its exit code.
        send_message(("127.0.0.1", port), "k",
                     {"kind": "run", "cmd": ["__PYTHON__", "-c",
                                             "import time; time.sleep(5)"],
                      "env": {}}, timeout=5.0)
        resp = send_message(("127.0.0.1", port), "k",
                            {"kind": "proc_poll"}, timeout=5.0)
        assert resp["has_proc"] is True and resp["rc"] is None
        send_message(("127.0.0.1", port), "k",
                     {"kind": "proc_stop"}, timeout=5.0)
    finally:
        task.stop()


def _worker_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_static_run_end_to_end():
    """Real launcher e2e: 3 local workers init tcp mode via rendezvous,
    allreduce, and verify identity env plumbed by the launcher."""
    script = (
        "import horovod_tpu as hvd, numpy as np\n"
        "hvd.init()\n"
        "assert hvd.size() == 3\n"
        "out = hvd.allreduce(np.ones(4, np.float32) * hvd.rank(),"
        " op=hvd.Sum, name='t')\n"
        "np.testing.assert_allclose(np.asarray(out), 3.0)\n"
        "assert hvd.local_size() == 3\n"
        "print('RANK_OK', hvd.rank())\n"
        "hvd.shutdown()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "3",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=scaled_timeout(180), env=_worker_env(),
        cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(3):
        assert "RANK_OK %d" % r in proc.stdout


def test_static_run_failure_tears_down_world():
    """One worker exits non-zero -> launcher kills the rest and reports
    failure (reference exit-propagation behavior)."""
    script = (
        "import os, time\n"
        "if os.environ['HOROVOD_RANK'] == '1':\n"
        "    raise SystemExit(3)\n"
        "time.sleep(600)\n")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=scaled_timeout(120), env=_worker_env(),
        cwd=REPO)
    assert proc.returncode != 0
    assert time.monotonic() - t0 < 60


def test_programmatic_run():
    from tests.utils.run_fn import rank_times_two
    from horovod_tpu.runner import run
    results = run(rank_times_two, np=2)
    assert results == [0, 2]


def test_programmatic_run_backend_kwargs():
    # Reference-signature compatibility: use_gloo accepted (TCP IS the
    # gloo-equivalent plane), use_mpi rejected loudly (absent by
    # design).
    from horovod_tpu.runner import run
    from tests.utils.run_fn import rank_times_two
    assert run(rank_times_two, np=1, use_gloo=True) == [0]
    with pytest.raises(ValueError, match="MPI"):
        run(rank_times_two, np=1, use_mpi=True)


def test_programmatic_run_elastic():
    # Reference horovod.run elastic parameters: min_np routes through
    # the elastic driver; results are the final world's per-rank
    # values over a real driver-rendezvous'd world.
    from tests.utils.run_fn import elastic_rank_value
    from horovod_tpu.runner import run
    results = run(elastic_rank_value, np=2, min_np=2,
                  elastic_timeout=60)
    assert results == [2, 12]


def test_lsf_host_parsing(monkeypatch):
    from horovod_tpu.runner import util
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4 nodeB 2")
    assert util.lsf_available()
    hosts = util.parse_lsf_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("nodeA", 4), ("nodeB", 2)]
    monkeypatch.delenv("LSB_MCPU_HOSTS")
    monkeypatch.setenv("LSB_HOSTS", "n1 n1 n1 n2")
    hosts = util.parse_lsf_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("n1", 3), ("n2", 1)]


def test_slurm_host_parsing(monkeypatch):
    from horovod_tpu.runner import util
    monkeypatch.setenv("SLURM_JOB_NODELIST", "node[01-03,07],gpu5")
    monkeypatch.setenv("SLURM_TASKS_PER_NODE", "4(x3),2")
    assert util.slurm_available()
    hosts = util.parse_slurm_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("node01", 4), ("node02", 4), ("node03", 4), ("node07", 2),
        ("gpu5", 2)]


def test_scheduler_hosts_fallback(monkeypatch):
    from horovod_tpu.runner import util
    for var in ("LSB_MCPU_HOSTS", "LSB_HOSTS", "SLURM_JOB_NODELIST",
                "SLURM_NODELIST"):
        monkeypatch.delenv(var, raising=False)
    assert util.scheduler_hosts() == []


def test_lsf_interleaved_hosts(monkeypatch):
    from horovod_tpu.runner import util
    monkeypatch.delenv("LSB_MCPU_HOSTS", raising=False)
    monkeypatch.setenv("LSB_HOSTS", "n1 n2 n1 n2")
    hosts = util.parse_lsf_hosts()
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("n1", 2), ("n2", 2)]


def test_scheduler_hosts_warns_on_malformed(monkeypatch, capsys):
    from horovod_tpu.runner import util
    monkeypatch.setenv("LSB_MCPU_HOSTS", "host1 4 host2")  # odd tokens
    for var in ("SLURM_JOB_NODELIST", "SLURM_NODELIST"):
        monkeypatch.delenv(var, raising=False)
    assert util.scheduler_hosts() == []
    assert "LSF detected but unusable" in capsys.readouterr().err


def test_undersized_scheduler_allocation_hard_fails(monkeypatch):
    # A Slurm/LSF allocation smaller than -np must abort (reference
    # launcher behavior), not silently oversubscribe the batch node.
    import pytest
    from horovod_tpu.runner import launch
    monkeypatch.setenv("SLURM_JOB_NODELIST", "node01")
    monkeypatch.setenv("SLURM_TASKS_PER_NODE", "2")
    with pytest.raises(SystemExit, match="2 slots < -np 4"):
        launch.run_commandline(["-np", "4", "true"])


def test_programmatic_run_env_overlay_does_not_leak():
    # run(env=...) reaches the workers but never mutates the caller env.
    import os
    from horovod_tpu.runner.run_api import run
    assert "HVD_TPU_TEST_OVERLAY" not in os.environ
    out = run(_echo_overlay, np=2, env={"HVD_TPU_TEST_OVERLAY": "yes"})
    assert out == ["yes", "yes"]
    assert "HVD_TPU_TEST_OVERLAY" not in os.environ


def _echo_overlay():
    import os
    return os.environ.get("HVD_TPU_TEST_OVERLAY")
