"""Multi-tenant pod scheduler tests (ISSUE 8).

Fast units exercise the packing plan, admission/preemption arbitration
(stub drivers), the REAL ElasticDriver's scheduler-preemption
bookkeeping (planned removal: no blacklist, no failure counts, backoff
reset, epoch bump), cross-tenant isolation of the drivers' books under
a simulated ``tenant.worker.die``, the tenant-scoped KV/spill
namespaces, and the tenant-labeled metric series in the merged
/metrics render.  The 2-tenant real-process e2es (injected tenant-A
death with tenant-B progress asserted; scheduler preemption restoring
from the r10 spill at the committed step) are ``slow``-marked to keep
the tier-1 wall-clock budget intact — CI runs them by node id.
"""

import os
import sys
import threading
import time

import pytest

from horovod_tpu.common import faultline, metrics
from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.scheduler import (DONE, PENDING, PREEMPTED,
                                           REJECTED, RUNNING,
                                           PodScheduler, TenantSpec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(cond, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# -- packing plan ----------------------------------------------------------

def test_tenant_spec_validation():
    with pytest.raises(ValueError):
        TenantSpec("", ["true"])
    with pytest.raises(ValueError):
        TenantSpec("t", ["true"], min_np=0)
    with pytest.raises(ValueError):
        TenantSpec("t", ["true"], min_np=4, max_np=2)


def test_plan_priority_packing_and_slack():
    sched = PodScheduler(FixedHosts({}), driver_factory=lambda t: None)

    class _T:
        def __init__(self, tid, prio, seq, min_np, max_np):
            self.spec = TenantSpec(tid, ["true"], priority=prio,
                                   min_np=min_np, max_np=max_np)
            self.seq = seq
            self.tenant_id = tid

    hi = _T("hi", 9, 1, 2, None)       # later admit, higher priority
    lo = _T("lo", 1, 0, 2, 3)
    tiny = _T("tiny", 1, 2, 3, 3)      # cannot fit: all-or-nothing
    order = sorted([lo, hi, tiny],
                   key=lambda t: (-t.spec.priority, t.seq))
    assert [t.tenant_id for t in order] == ["hi", "lo", "tiny"]
    plan = sched._plan({"h1": 2, "h2": 2}, order)
    # hi (priority 9) fills first, lo takes the rest, tiny gets NOTHING
    # rather than a useless partial fill below its min_np.
    assert sum(plan["hi"].values()) == 2
    assert sum(plan["lo"].values()) == 2
    assert plan["tiny"] == {}
    # With more capacity slack flows in priority order up to max_np —
    # the unbounded tenant absorbs the remainder, deterministically
    # host-ordered.
    plan = sched._plan({"h1": 4, "h2": 4},
                       [t for t in order if t is not tiny])
    assert sum(plan["hi"].values()) == 6   # 8 - lo's min of 2
    assert sum(plan["lo"].values()) == 2   # slack went to hi first
    assert plan["hi"] == {"h1": 2, "h2": 4}
    assert plan["lo"] == {"h1": 2}


# -- admission / preemption arbitration (stub drivers) ---------------------

class _StubDriver:
    def __init__(self, tenant):
        self.tenant = tenant
        self.preempts = []
        self.resumes = 0
        self._stop = threading.Event()

    def run(self):
        self._stop.wait()
        return 0

    def scheduler_preempt(self, reason):
        self.preempts.append(reason)

    def scheduler_resume(self):
        self.resumes += 1

    def request_stop(self):
        self._stop.set()

    def finish(self):
        self._stop.set()


def _stub_scheduler(pod):
    return PodScheduler(FixedHosts(pod), driver_factory=_StubDriver,
                        tick_secs=0.05)


def test_admission_preemption_and_resume_cycle():
    metrics.reset()
    sched = _stub_scheduler({"h1": 2})
    try:
        assert sched.admit(TenantSpec("A", ["true"], priority=1,
                                      min_np=2, max_np=2)) == RUNNING
        assert sched.allocation("A") == {"h1": 2}
        # Higher-priority admission preempts A via the drain path.
        assert sched.admit(TenantSpec("B", ["true"], priority=5,
                                      min_np=2, max_np=2)) == RUNNING
        assert sched.tenant_state("A") == PREEMPTED
        assert sched.allocation("A") == {}
        assert sched.tenant_driver("A").preempts == \
            ["priority contention"]
        # Fairness series moved: A books a preemption + a pending
        # shortfall, B holds the slots.
        assert metrics.series_sum("tenant_preemptions_total",
                                  tenant="A") == 1
        assert metrics.series_sum("tenant_slots", tenant="A",
                                  state="pending") == 2
        assert metrics.series_sum("tenant_slots", tenant="B",
                                  state="allocated") == 2
        # B finishes -> the freed slots resume A at the next tick.
        sched.tenant_driver("B").finish()
        assert _wait_for(lambda: sched.tenant_rc("B") == 0)
        sched.tick()
        assert sched.tenant_state("B") == DONE
        assert sched.tenant_state("A") == RUNNING
        assert sched.tenant_driver("A").resumes == 1
        assert sched.allocation("A") == {"h1": 2}
        # A's wait latency (preempt -> resume) was observed.
        snap = metrics.snapshot()["tenant_wait_seconds"]["series"]
        waits = [r for r in snap if r["labels"].get("tenant") == "A"]
        assert waits and waits[0]["count"] >= 1
    finally:
        sched.stop(timeout=5)


def test_admission_pends_without_capacity_then_starts():
    sched = _stub_scheduler({"h1": 1})
    try:
        assert sched.admit(TenantSpec("A", ["true"], priority=3,
                                      min_np=1, max_np=1)) == RUNNING
        # Equal priority cannot preempt: B waits instead.
        assert sched.admit(TenantSpec("B", ["true"], priority=3,
                                      min_np=1, max_np=1)) == PENDING
        assert sched.tenant_state("A") == RUNNING
        sched.tenant_driver("A").finish()
        assert _wait_for(lambda: sched.tenant_rc("A") == 0)
        sched.tick()
        assert sched.tenant_state("B") == RUNNING
    finally:
        sched.stop(timeout=5)


def test_admit_injection_refused_leaves_tenants_untouched(monkeypatch):
    sched = _stub_scheduler({"h1": 2})
    try:
        assert sched.admit(TenantSpec("A", ["true"], priority=1,
                                      min_np=2)) == RUNNING
        monkeypatch.setenv("HVD_TPU_FAULT", "scheduler.admit:drop")
        faultline.reset()
        assert sched.admit(TenantSpec("B", ["true"],
                                      priority=9, min_np=1)) == REJECTED
        # The refusal never disturbed the running tenant.
        assert sched.tenant_state("A") == RUNNING
        assert sched.allocation("A") == {"h1": 2}
        assert sched.tenant_driver("A").preempts == []
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
        sched.stop(timeout=5)


def test_lost_preempt_notice_is_reissued(monkeypatch):
    """scheduler.preempt.notice drop: the preemption order is lost for
    one tick; the replanner must re-issue it on the next tick until the
    pod converges (idempotent preemption application)."""
    sched = _stub_scheduler({"h1": 1})
    try:
        assert sched.admit(TenantSpec("A", ["true"], priority=1,
                                      min_np=1)) == RUNNING
        monkeypatch.setenv("HVD_TPU_FAULT",
                           "scheduler.preempt.notice:drop@times=1")
        faultline.reset()
        sched.admit(TenantSpec("B", ["true"], priority=9, min_np=1))
        # The admit-tick's preemption order was dropped: A still runs.
        assert sched.tenant_state("A") == RUNNING
        assert sched.tenant_driver("A").preempts == []
        # The next tick re-issues it.
        sched.tick()
        assert sched.tenant_state("A") == PREEMPTED
        assert sched.tenant_driver("A").preempts == \
            ["priority contention"]
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
        sched.stop(timeout=5)


# -- real-driver bookkeeping -----------------------------------------------

class _AliveProc:
    """Fake worker process: alive until the test (or terminate) sets an
    exit code.  terminate() exits with the DRAIN code — a drain-capable
    worker answering SIGTERM."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self, grace=None):
        from horovod_tpu.elastic.worker import DRAIN_EXIT_CODE
        self.terminated = True
        if self.rc is None:
            self.rc = DRAIN_EXIT_CODE


def _close_driver(driver):
    driver._server._server.server_close()
    driver._kv._httpd.server_close()


def test_scheduler_preemption_is_planned_removal():
    """ISSUE 8 satellite: a scheduler preemption rides the EXACT rc=85
    drained-slot bookkeeping from r10 — it never increments
    HOROVOD_HOST_FAILURE_THRESHOLD counts, never lands a host on the
    blacklist, resets the respawn backoff, and bumps the epoch
    proactively; resume respawns the world."""
    from horovod_tpu.elastic.scheduler import _TenantSlotView
    view = _TenantSlotView()
    view.set({"h": 2})
    d = ElasticDriver(["true"], view, min_np=2, max_np=2,
                      failure_threshold=1, elastic_timeout=0.2,
                      tenant_id="low", tenant_priority=1)
    d._make_worker_proc = lambda slot, env: _AliveProc()
    try:
        d._hosts.update_available_hosts()
        d._recompute_world("startup")
        assert len(d._procs) == 2
        epoch0 = d._epoch
        d._spawn_backoff[("h", 0)] = 16.0  # pre-existing throttle
        view.set({})
        d.scheduler_preempt("higher-priority admission")
        assert d.held()
        assert d._epoch == epoch0 + 1          # proactive epoch bump
        # Every worker was drain-terminated, not killed.
        procs = list(d._procs.values())
        assert procs and all(p.terminated for p in procs)
        assert d._check_procs() is False       # reap the rc=85 exits
        # The removal is PLANNED: zero failure counts, zero blacklist
        # entries, respawn backoff reset.
        assert d._registry._failures == {}
        assert d._registry.blacklisted_hosts() == []
        assert d._spawn_backoff == {}
        # Held: the below-min deadline (elastic_timeout=0.2) must NOT
        # fail the parked driver.
        time.sleep(0.3)
        assert d._check_procs() is False
        # Resume re-forms the world from the handed-back slots.
        view.set({"h": 2})
        d.scheduler_resume()
        assert not d.held()
        assert d._epoch == epoch0 + 2
        assert len(d._procs) == 2
        assert d._registry.blacklisted_hosts() == []
    finally:
        _close_driver(d)


def test_cross_tenant_isolation_bookkeeping():
    """ISSUE 8 satellite (fast half of the injection certification):
    tenant A's worker dies — as ``tenant.worker.die`` would kill it —
    and every book of tenant B stays untouched: no blacklist entry, no
    failure count, no epoch bump, allocation intact, worker alive."""
    spawned = []  # (tenant_id, slot, proc) per spawn, in spawn order

    def factory(tenant):
        d = ElasticDriver(
            ["true"], tenant.view, min_np=tenant.spec.min_np,
            max_np=tenant.spec.max_np, failure_threshold=10,
            discovery_interval=0.05, start_timeout=5,
            respawn_backoff_base=0.05, respawn_backoff_cap=0.2,
            tenant_id=tenant.tenant_id,
            tenant_priority=tenant.spec.priority)

        def mk(slot, env, d=d):
            p = _AliveProc()
            spawned.append((d.tenant_id, slot, p))
            return p

        d._make_worker_proc = mk
        return d

    def procs_of(tid):
        return [p for t, _s, p in spawned if t == tid]

    sched = PodScheduler(FixedHosts({"hA": 1, "hB": 1}),
                         driver_factory=factory, tick_secs=0.05)
    try:
        assert sched.admit(TenantSpec("A", ["true"], priority=1,
                                      min_np=1, max_np=1)) == RUNNING
        assert sched.admit(TenantSpec("B", ["true"], priority=1,
                                      min_np=1, max_np=1)) == RUNNING
        da, db = sched.tenant_driver("A"), sched.tenant_driver("B")
        assert _wait_for(lambda: len(procs_of("A")) == 1
                         and len(procs_of("B")) == 1)
        assert _wait_for(lambda: db._epoch >= 1)
        host_a = [s for t, s, _p in spawned if t == "A"][0][0]
        host_b = [s for t, s, _p in spawned if t == "B"][0][0]
        assert host_a != host_b  # disjoint slot partitions
        epoch_b = db._epoch
        # tenant.worker.die@tenant=A fires: A's worker drops dead.
        procs_of("A")[0].rc = 43
        # A's own driver books the failure and re-forms A's world
        # (epoch bump + respawn) ...
        assert _wait_for(lambda: da._registry._failures.get(
            host_a, 0) >= 1)
        assert _wait_for(lambda: da._epoch > 1)
        assert _wait_for(lambda: len(procs_of("A")) >= 2)  # respawned
        # ... while EVERY book of tenant B is untouched: no blacklist,
        # no failure counts, no epoch bump, allocation + worker intact.
        time.sleep(0.3)  # several scheduler + driver ticks
        assert db._registry.blacklisted_hosts() == []
        assert db._registry._failures == {}
        assert db._epoch == epoch_b
        assert sched.allocation("B") == {host_b: 1}
        assert procs_of("B")[0].rc is None  # B's worker never touched
        assert len(procs_of("B")) == 1      # and never respawned
        assert sched.tenant_state("B") == RUNNING
        # And B's host never shows in A's books either (disjoint sets).
        assert host_b not in da._registry._failures
        # A's failure NEVER blacklisted a host at threshold 10.
        assert da._registry.blacklisted_hosts() == []
    finally:
        sched.stop(timeout=5)


def test_tenant_worker_die_targeting(monkeypatch):
    """@tenant= conditions select exactly one tenant's processes, and
    the commit-seam plant fires into the metrics plane."""
    monkeypatch.setenv("HVD_TPU_FAULT",
                       "tenant.worker.die:delay:0@tenant=A")
    faultline.reset()
    metrics.reset()
    try:
        monkeypatch.setenv("HOROVOD_TENANT_ID", "B")
        assert faultline.armed("tenant.worker.die") is None
        monkeypatch.setenv("HOROVOD_TENANT_ID", "A")
        assert faultline.armed("tenant.worker.die") is not None
        # The State.commit plant fires it (delay:0 = observable no-op).
        from horovod_tpu.elastic.state import ObjectState
        ObjectState(batch=0).commit()
        assert metrics.series_sum("fault_injections_total",
                                  site="tenant.worker.die") == 1
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        monkeypatch.delenv("HOROVOD_TENANT_ID")
        faultline.reset()
        metrics.reset()


# -- tenant-scoped namespaces ----------------------------------------------

def test_rendezvous_kv_tenant_namespace(monkeypatch):
    """One shared KV server, two tenants, the same key: the namespace
    prefix keeps the entries disjoint, and HOROVOD_TENANT_ID wires the
    default."""
    from horovod_tpu.runner.http_client import RendezvousClient
    from horovod_tpu.runner.http_server import RendezvousServer
    monkeypatch.delenv("HOROVOD_TENANT_ID", raising=False)
    server = RendezvousServer(host="127.0.0.1", secret="s")
    port = server.start()
    try:
        addr = "127.0.0.1:%d" % port
        a = RendezvousClient(addr, secret="s", namespace="A")
        b = RendezvousClient(addr, secret="s", namespace="B")
        plain = RendezvousClient(addr, secret="s")
        a.put("jax_coordinator:0", "10.0.0.1:99")
        b.put("jax_coordinator:0", "10.0.0.2:99")
        assert a.get("jax_coordinator:0") == "10.0.0.1:99"
        assert b.get("jax_coordinator:0") == "10.0.0.2:99"
        assert plain.get("jax_coordinator:0") is None
        # Env-wired default namespace matches the explicit one.
        monkeypatch.setenv("HOROVOD_TENANT_ID", "A")
        env_client = RendezvousClient(addr, secret="s")
        assert env_client.get("jax_coordinator:0") == "10.0.0.1:99"
        a.delete("jax_coordinator:0")
        assert a.get("jax_coordinator:0") is None
        assert b.get("jax_coordinator:0") == "10.0.0.2:99"
    finally:
        server.stop()


def test_spill_dir_tenant_namespace(tmp_path, monkeypatch):
    """Two tenants sharing HOROVOD_STATE_SPILL_DIR spill into disjoint
    subdirectories: tenant B can never restore tenant A's state."""
    from horovod_tpu.elastic import spill
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_TENANT_ID", "A")
    spill.write(3, b"tenant-A-state", "r0")
    assert spill.load_newest() == (3, b"tenant-A-state")
    assert (tmp_path / "tenant-A").is_dir()
    monkeypatch.setenv("HOROVOD_TENANT_ID", "B")
    assert spill.load_newest() is None
    assert not spill.have_evidence()
    spill.write(1, b"tenant-B-state", "r0")
    assert spill.load_newest() == (1, b"tenant-B-state")
    monkeypatch.setenv("HOROVOD_TENANT_ID", "A")
    assert spill.load_newest() == (3, b"tenant-A-state")
    # Without a tenant id the legacy un-namespaced path is untouched.
    monkeypatch.delenv("HOROVOD_TENANT_ID")
    assert spill.load_newest() is None


def test_merged_render_labels_tenant_series():
    """ISSUE 8 satellite: the fleet-wide /metrics merge rank-labels
    tenant series correctly — tenant labels survive the merge and each
    source keeps its own rank label."""
    metrics.reset()
    try:
        metrics.gauge("tenant_slots", tenant="A",
                      state="allocated").set(2)
        metrics.counter("tenant_preemptions_total", tenant="A").inc()
        driver_model = metrics.snapshot()
        metrics.reset()
        metrics.counter("engine_cycles_total").inc(5)
        worker_model = metrics.snapshot()
        text = metrics.render_merged([("scheduler", driver_model),
                                      ("0", worker_model)])
        assert ('tenant_slots{rank="scheduler",state="allocated",'
                'tenant="A"} 2') in text
        assert ('tenant_preemptions_total{rank="scheduler",'
                'tenant="A"} 1') in text
        assert 'engine_cycles_total{rank="0"} 5' in text
        # One HELP/TYPE per family, as the exposition format requires.
        assert text.count("# TYPE tenant_slots gauge") == 1
    finally:
        metrics.reset()


# -- real-process e2e (slow: 2 tenants, real elastic worlds) ---------------

TENANT_WORKER = """
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0)

def note(line):
    with open(os.environ["TENANT_LOG"], "a") as f:
        f.write(line + "\\n")

@elastic.run
def train(state):
    note("ENTER batch=%d commit=%d" % (state.batch, state._commit_id))
    while state.batch < int(os.environ["TENANT_BATCHES"]):
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="b%d" % state.batch)
        state.batch += 1
        note("STEP %d" % state.batch)
        time.sleep(float(os.environ.get("TENANT_STEP_SECS", "0.05")))
        state.commit()
    note("DONE batch=%d" % state.batch)

train(state)
"""


def _tenant_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_ELASTIC_DRIVER_ADDR", None)
    env.update(extra or {})
    return env


def _lines(path):
    try:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


@pytest.mark.slow
def test_scheduler_two_tenant_isolation_e2e(tmp_path):
    """ISSUE 8 acceptance: with ``tenant.worker.die`` armed against
    tenant A (die at A's 3rd epoch-1 commit), tenant B completes all
    its steps with NO blacklist entries and NO drained-slot misbooking
    — and A itself recovers (the respawn runs in a later epoch, where
    the injection no longer fires) and finishes."""
    script = tmp_path / "train.py"
    script.write_text(TENANT_WORKER)
    log_a, log_b = tmp_path / "a.log", tmp_path / "b.log"
    base = _tenant_env({
        "HVD_TPU_FAULT":
            "tenant.worker.die:die:43@tenant=A@epoch=1@after=2",
    })
    sched = PodScheduler(
        FixedHosts({"127.0.0.1": 2}), env=base, tick_secs=0.2,
        failure_threshold=10,       # A's own death must not strand A
        start_timeout=60)
    try:
        sched.start()
        assert sched.admit(TenantSpec(
            "A", [sys.executable, str(script)], priority=1,
            min_np=1, max_np=1,
            env={"TENANT_LOG": str(log_a), "TENANT_BATCHES": "6"},
        )) == RUNNING
        assert sched.admit(TenantSpec(
            "B", [sys.executable, str(script)], priority=1,
            min_np=1, max_np=1,
            env={"TENANT_LOG": str(log_b), "TENANT_BATCHES": "6"},
        )) == RUNNING
        assert _wait_for(lambda: sched.tenant_state("A") == DONE
                         and sched.tenant_state("B") == DONE,
                         timeout=240, interval=0.25), (
            "A=%s B=%s\nA log: %r\nB log: %r"
            % (sched.tenant_state("A"), sched.tenant_state("B"),
               _lines(log_a), _lines(log_b)))
        da, db = sched.tenant_driver("A"), sched.tenant_driver("B")
        # The injection really fired: A died once and re-entered at
        # its committed step (the epoch-2 worker restores commit 3).
        a_lines = _lines(log_a)
        assert a_lines.count("DONE batch=6") == 1, a_lines
        assert len([l for l in a_lines if l.startswith("ENTER")]) >= 2, \
            a_lines
        # The failure was reaped and A's world re-formed (a clean
        # recovery rightly CLEARS the streak — r8 record_success — so
        # the monotonic counter and epoch are the injection's proof).
        assert metrics.series_sum("elastic_worker_failures_total",
                                  tenant="A") >= 1
        assert da._epoch >= 2
        # Isolation: B's books are spotless — no blacklist, no failure
        # counts, no epoch churn — and B advanced through all steps.
        b_lines = _lines(log_b)
        assert "DONE batch=6" in b_lines, b_lines
        assert [l for l in b_lines if l.startswith("STEP")] == \
            ["STEP %d" % i for i in range(1, 7)], b_lines
        assert db._registry.blacklisted_hosts() == []
        assert db._registry._failures == {}
        assert db._epoch == 1
    finally:
        sched.stop(timeout=30)


@pytest.mark.slow
def test_scheduler_preemption_restores_from_spill_e2e(tmp_path):
    """ISSUE 8 acceptance: a higher-priority admission drain-preempts
    the running tenant (planned removal: commit + spill + rc=85, no
    blacklist), the displacing tenant completes, and the preempted
    tenant resumes FROM ITS r10 SPILL at the committed step."""
    script = tmp_path / "train.py"
    script.write_text(TENANT_WORKER)
    log_low, log_high = tmp_path / "low.log", tmp_path / "high.log"
    base = _tenant_env({
        "HOROVOD_STATE_SPILL_DIR": str(tmp_path / "spills"),
        "HOROVOD_PREEMPT_GRACE_SECS": "20",
    })
    sched = PodScheduler(FixedHosts({"127.0.0.1": 1}), env=base,
                         tick_secs=0.2, start_timeout=60)
    try:
        sched.start()
        assert sched.admit(TenantSpec(
            "low", [sys.executable, str(script)], priority=1,
            min_np=1, max_np=1,
            env={"TENANT_LOG": str(log_low), "TENANT_BATCHES": "40",
                 "TENANT_STEP_SECS": "0.2"},
        )) == RUNNING
        # Let the low tenant make real committed progress first.
        assert _wait_for(
            lambda: len([l for l in _lines(log_low)
                         if l.startswith("STEP")]) >= 3,
            timeout=120, interval=0.25), _lines(log_low)
        assert sched.admit(TenantSpec(
            "high", [sys.executable, str(script)], priority=9,
            min_np=1, max_np=1,
            env={"TENANT_LOG": str(log_high), "TENANT_BATCHES": "3",
                 "TENANT_STEP_SECS": "0.05"},
        )) in (RUNNING, PENDING)
        assert _wait_for(lambda: sched.tenant_state("low") == PREEMPTED,
                         timeout=60, interval=0.25)
        d_low = sched.tenant_driver("low")
        # The preemption is a PLANNED removal: nothing booked as a
        # failure while low is parked.
        assert d_low._registry.blacklisted_hosts() == []
        assert d_low._registry._failures == {}
        assert sched.allocation("low") == {}
        # The displacing tenant runs to completion on the freed slot,
        # then low resumes ...
        assert _wait_for(lambda: sched.tenant_state("high") == DONE,
                         timeout=240, interval=0.25), _lines(log_high)
        assert _wait_for(lambda: sched.tenant_state("low") == RUNNING,
                         timeout=60, interval=0.25)
        # ... from its spill at the committed step, NOT from zero: the
        # resumed worker's ENTER line carries the pre-preemption
        # commit.
        def resumed_enter():
            enters = [l for l in _lines(log_low)
                      if l.startswith("ENTER")]
            return len(enters) >= 2 and enters[-1] != enters[0]
        assert _wait_for(resumed_enter, timeout=120, interval=0.25), \
            _lines(log_low)
        enters = [l for l in _lines(log_low) if l.startswith("ENTER")]
        resumed_batch = int(enters[-1].split("batch=")[1].split()[0])
        assert resumed_batch >= 3, enters
        assert d_low._registry.blacklisted_hosts() == []
        assert metrics.series_sum("tenant_preemptions_total",
                                  tenant="low") >= 1
    finally:
        sched.stop(timeout=30)
