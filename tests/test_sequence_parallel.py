"""Ring attention + Ulysses vs exact attention (beyond-reference SP/CP;
SURVEY.md §5 scopes these as TPU-idiomatic extensions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.ring_attention import (local_attention,
                                                 ring_attention)
from horovod_tpu.parallel.ulysses import ulysses_attention

B, S, H, D = 2, 32, 8, 16
SP = 8


def _qkv(kv_heads=H, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, kv_heads, D).astype(np.float32)
    v = rng.randn(B, S, kv_heads, D).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _run_sp(fn, q, k, v):
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    mapped = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    return jax.jit(mapped)(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_exact(hvd_world, causal):
    q, k, v = _qkv()
    expected = local_attention(q, k, v, causal=causal)
    got = _run_sp(lambda a, b, c: ring_attention(
        a, b, c, axis_name="sp", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_gqa(hvd_world):
    q, k, v = _qkv(kv_heads=2, seed=1)
    expected = local_attention(q, k, v, causal=True)
    got = _run_sp(lambda a, b, c: ring_attention(
        a, b, c, axis_name="sp", causal=True), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_exact(hvd_world, causal):
    q, k, v = _qkv(seed=2)
    expected = local_attention(q, k, v, causal=causal)
    got = _run_sp(lambda a, b, c: ulysses_attention(
        a, b, c, axis_name="sp", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_long_sequence_memory_shape(hvd_world):
    # 8 shards x 64 local tokens: just checks shapes/finiteness at a size
    # where full [S, S] scores per shard would be 512x512.
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 512, 4, 8).astype(np.float32))
    k, v = q, q
    out = _run_sp(lambda a, b, c: ring_attention(
        a, b, c, axis_name="sp", causal=True), q, k, v)
    assert out.shape == (1, 512, 4, 8)
    assert bool(jnp.all(jnp.isfinite(out)))
