"""Continuous-batching serving plane tests (ISSUE 11).

Fast units exercise the router's admission policy (coalescing under
max-wait, full-batch dispatch, deadline expiry, queue-depth
accounting, requeue-at-front ordering), the ``serving.request.drop``
injection seam, the autoscale decision table and the Autoscaler's
grow-now/shrink-after-cooldown asymmetry, the durable work queue's
claim/sweep/idempotence invariants, the VersionStore's corrupt-blob
fallback, the newest-version election, the in-process replica set's
kill-with-requeue (no request lost) + hot-swap convergence, the HTTP
front door, and the ``PodScheduler.resize``/``poke`` satellite fix.

The 2-proc real-process e2es — hot swap certified under
``serving.replica.die`` injection (no request lost, survivors elect
the newest version) and the traffic-driven tenant autoscaler — are
``slow``-marked per the 870 s tier-1 cap; CI's `serving-smoke` job
runs them by node id.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from horovod_tpu.common import faultline, metrics
from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.elastic.scheduler import (DONE, RUNNING, PodScheduler,
                                           TenantSpec)
from horovod_tpu.jax.functions import elect_newest
from horovod_tpu.serving import (Autoscaler, DeploymentSpec,
                                 FileWorkQueue, ReplicaSet, Router,
                                 VersionStore, admit_deployment,
                                 autoscale_decision,
                                 install_http_frontend, swap_to,
                                 tenant_autoscaler)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    monkeypatch.delenv("HVD_TPU_FAULT", raising=False)
    faultline.reset()
    yield
    faultline.reset()


def _wait_for(cond, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# -- router admission policy -----------------------------------------------

def test_router_full_batch_dispatches_without_waiting():
    router = Router(max_batch_size=4, max_wait_us=5_000_000)
    for i in range(4):
        router.submit("d", i)
    t0 = time.monotonic()
    batch = router.next_batch("d", timeout=1.0)
    # A FULL batch must not wait out the max-wait window.
    assert time.monotonic() - t0 < 1.0
    assert [r.payload for r in batch] == [0, 1, 2, 3]
    assert all(r.attempts == 1 for r in batch)


def test_router_max_wait_closes_partial_batch():
    router = Router(max_batch_size=64, max_wait_us=80_000)
    router.submit("d", "a")
    router.submit("d", "b")
    t0 = time.monotonic()
    batch = router.next_batch("d", timeout=2.0)
    elapsed = time.monotonic() - t0
    assert [r.payload for r in batch] == ["a", "b"]
    # The batch closed because the OLDEST request aged past max-wait —
    # not instantly, not at the 2 s poll timeout.
    assert 0.04 <= elapsed < 1.0


def test_router_deadline_expiry_resolves_without_dispatch():
    metrics.reset()
    router = Router(max_batch_size=8, max_wait_us=5_000_000)
    req = router.submit("d", "x", timeout_s=0.03)
    assert router.next_batch("d", timeout=0.3) == []
    assert req.done and req.outcome == "deadline"
    assert metrics.series_sum("serving_requests_total",
                              deployment="d", outcome="deadline") == 1
    assert router.depth("d") == 0


def test_router_queue_depth_accounting():
    metrics.reset()
    router = Router(max_batch_size=2, max_wait_us=0)
    for i in range(3):
        router.submit("d", i)
    assert metrics.series_sum("serving_queue_depth", deployment="d") == 3
    batch = router.next_batch("d", timeout=1.0)
    assert len(batch) == 2
    assert metrics.series_sum("serving_queue_depth", deployment="d") == 1
    router.complete(batch, ["r0", "r1"])
    assert metrics.series_sum("serving_requests_total",
                              deployment="d", outcome="ok") == 2


def test_router_requeue_reenters_at_front_in_arrival_order():
    router = Router(max_batch_size=2, max_wait_us=0)
    for i in range(4):
        router.submit("d", i)
    first = router.next_batch("d", timeout=1.0)
    assert [r.payload for r in first] == [0, 1]
    router.requeue(first)          # failed dispatch hands them back
    again = router.next_batch("d", timeout=1.0)
    # Arrival order preserved: the requeued pair outranks 2, 3.
    assert [r.payload for r in again] == [0, 1]
    assert [r.attempts for r in again] == [2, 2]


def test_router_requeue_expires_dead_requests():
    router = Router(max_batch_size=2, max_wait_us=0)
    req = router.submit("d", "x", timeout_s=0.01)
    batch = router.next_batch("d", timeout=1.0)
    assert [r.payload for r in batch] == ["x"]
    time.sleep(0.03)
    router.requeue(batch)
    assert req.done and req.outcome == "deadline"
    assert router.depth("d") == 0


def test_request_drop_injection_never_queues(monkeypatch):
    metrics.reset()
    monkeypatch.setenv("HVD_TPU_FAULT",
                       "serving.request.drop:drop@times=1")
    faultline.reset()
    router = Router(max_batch_size=8, max_wait_us=0)
    dropped = router.submit("d", "a")
    assert dropped.done and dropped.outcome == "dropped"
    assert router.depth("d") == 0
    assert metrics.series_sum("serving_requests_total",
                              deployment="d", outcome="dropped") == 1
    # Refused admissions never disturb queued traffic: the next
    # submit (injection exhausted) queues and serves normally.
    ok = router.submit("d", "b")
    batch = router.next_batch("d", timeout=1.0)
    router.complete(batch, ["r"])
    assert ok.outcome == "ok" and ok.result == "r"


# -- autoscale policy -------------------------------------------------------

def test_autoscale_decision_table():
    cases = [
        # (depth, replicas, min, max, want) at up=4, down=0.5
        (0, 1, 1, 8, 1),     # idle at the floor: hold
        (3, 1, 1, 8, 1),     # below up-threshold: hold
        (4, 1, 1, 8, 1),     # exactly at threshold: ceil(4/4) = 1
        (9, 1, 1, 8, 3),     # backlog 9 -> ceil(9/4) replicas
        (64, 1, 1, 4, 4),    # growth bounded by max
        (16, 4, 1, 8, 4),    # per-replica 4 -> ceil(16/4) = 4: hold
        (1, 4, 1, 8, 3),     # drained: release exactly ONE
        (0, 4, 3, 8, 3),     # shrink respects the min floor
        (0, 1, 1, None, 1),  # unbounded max, floor holds
        (100, 2, 1, None, 25),
    ]
    for depth, replicas, mn, mx, want in cases:
        got = autoscale_decision(depth, replicas, mn, mx,
                                 up_qdepth=4.0, down_qdepth=0.5)
        assert got == want, (depth, replicas, mn, mx, got, want)


def test_autoscaler_grows_immediately_shrinks_after_cooldown():
    metrics.reset()
    depth = [12.0]
    current = [1]
    applied = []

    scaler = Autoscaler(lambda: depth[0], lambda: current[0],
                        applied.append, min_replicas=1, max_replicas=8,
                        deployment="d", interval=60, cooldown=0.1,
                        up_qdepth=4.0, down_qdepth=0.5)
    scaler.tick()
    assert applied == [3]          # growth is never cooldown-gated
    current[0] = 3
    depth[0] = 0.0
    scaler.tick()
    assert applied == [3]          # shrink inside the cooldown: held
    time.sleep(0.12)
    scaler.tick()
    assert applied == [3, 2]       # cooldown passed: release one
    # The observed depth is republished for the fleet scrape.
    assert metrics.series_sum("serving_queue_depth", deployment="d") == 0


def test_autoscaler_records_scale_up_convergence():
    current = [1]
    scaler = Autoscaler(lambda: 8.0, lambda: current[0],
                        lambda n: None, min_replicas=1, max_replicas=4,
                        interval=60, cooldown=0.0,
                        up_qdepth=4.0, down_qdepth=0.5)
    scaler.tick()                  # orders 1 -> 2
    assert scaler.decisions[-1] == {"from": 1, "to": 2, "depth": 8.0}
    assert scaler.last_scale_up_secs is None
    current[0] = 2                 # the order lands (replica spawned)
    scaler.tick()
    assert scaler.last_scale_up_secs is not None
    assert scaler.last_scale_up_secs >= 0.0


# -- durable work queue -----------------------------------------------------

def test_workqueue_claim_complete_and_idempotent_done(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"))
    rid = q.submit({"x": 1})
    assert q.depth() == 1
    claims = q.claim(8)
    assert len(claims) == 1 and claims[0].payload == {"x": 1}
    assert q.depth() == 0
    q.complete(claims[0], {"y": 2})
    assert q.result(rid) == {"y": 2}
    assert q.done_count() == 1
    # A duplicate complete (at-least-once redo) collapses by req id.
    q.complete(claims[0], {"y": 2})
    assert q.done_count() == 1


def test_workqueue_rejects_separator_in_request_id(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"))
    with pytest.raises(ValueError):
        q.submit({}, req_id="a.b")
    with pytest.raises(ValueError):
        q.submit({}, req_id="a/b")


def test_workqueue_sweep_requeues_dead_claimants_work(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"))
    rid = q.submit({"x": 7})
    # Simulate a replica that claimed and then died: move the pending
    # file into claimed/ stamped with a pid that is REALLY dead.
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    os.rename(os.path.join(str(tmp_path / "q"), "pending",
                           "req-%s.json" % rid),
              os.path.join(str(tmp_path / "q"), "claimed",
                           "req-%s.%d.json" % (rid, proc.pid)))
    assert q.depth() == 0
    assert q.sweep_dead_claimants() == 1
    assert q.depth() == 1          # the request, not the claim, survived
    claims = q.claim(1)
    assert claims and claims[0].req_id == rid


def test_workqueue_sweep_releases_already_completed_claim(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"))
    rid = q.submit({"x": 7})
    claims = q.claim(1)
    q.complete(claims[0], {"ok": True})
    # Re-create the claim as a dead pid would have left it (died after
    # writing done/, before releasing): sweep must RELEASE, not redo.
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    path = os.path.join(str(tmp_path / "q"), "claimed",
                        "req-%s.%d.json" % (rid, proc.pid))
    with open(path, "w") as f:
        f.write(json.dumps({"x": 7}))
    assert q.sweep_dead_claimants() == 0
    assert q.depth() == 0 and q.done_count() == 1
    assert not os.path.exists(path)


def test_workqueue_stale_claim_requeued_even_with_live_pid(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"), stale_claim_secs=0.01)
    rid = q.submit({"x": 7})
    q.claim(1)                     # claimed by THIS live process
    time.sleep(0.05)
    assert q.sweep_dead_claimants() == 1   # wedged-replica backstop
    assert q.depth() == 1
    assert q.result(rid) is None


def test_workqueue_stale_window_runs_from_claim_not_submit(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"), stale_claim_secs=0.2)
    q.submit({"x": 7})
    # Backlog older than the stale window: the CLAIM must still be
    # fresh (rename preserves the submit mtime; claim re-stamps it),
    # or every old request would be double-served the moment it was
    # claimed.
    time.sleep(0.3)
    assert len(q.claim(1)) == 1
    assert q.sweep_dead_claimants() == 0
    assert q.depth() == 0


def test_workqueue_generated_ids_claim_in_arrival_order(tmp_path):
    q = FileWorkQueue(str(tmp_path / "q"))
    rids = [q.submit({"i": i}) for i in range(6)]
    claims = q.claim(6)
    assert [c.req_id for c in claims] == rids


# -- version store + hot swap ----------------------------------------------

def test_version_store_publish_scan_and_corrupt_fallback(tmp_path):
    store = VersionStore(str(tmp_path))
    assert store.version() == 0 and store.newest() is None
    p1 = store.publish(1, {"w": 1})
    p2 = store.publish(2, {"w": 2})
    assert store.version() == 2
    assert store.newest() == (2, {"w": 2})
    assert store.newest(min_version=2) is None
    # Corrupt the newest blob: the load path re-validates and falls
    # back to the previous version instead of half-loading weights.
    with open(p2, "wb") as f:
        f.write(b"torn publish garbage")
    assert store.newest() == (1, {"w": 1})
    assert p1  # both publishes returned real paths
    with pytest.raises(ValueError):
        store.publish(0, {})


def test_swap_to_loads_newest_and_commits(tmp_path):
    store = VersionStore(str(tmp_path))
    store.publish(3, {"w": 3})

    class _State:
        version = 0
        weights = None

        def __init__(self):
            self.commits = 0

        def commit(self):
            self.commits += 1

    state = _State()
    assert swap_to(store, state) is True
    assert (state.version, state.weights) == (3, {"w": 3})
    assert state.commits == 1      # the commit IS the election evidence
    assert swap_to(store, state) is False  # idempotent at the newest
    # A corrupt newest blob keeps the replica serving its current
    # version rather than swapping to garbage.
    path = store.publish(4, {"w": 4})
    with open(path, "wb") as f:
        f.write(b"bad")
    assert swap_to(store, state) is False
    assert state.version == 3 and state.commits == 1


def test_version_store_corrupt_head_read_once_until_new_publish(
        tmp_path):
    metrics.reset()
    store = VersionStore(str(tmp_path))
    path = store.publish(1, {"w": 1})
    with open(path, "wb") as f:
        f.write(b"torn")
    assert store.newest() is None
    failures = metrics.series_sum("spill_crc_failures_total")
    assert failures >= 1
    # Polling again must NOT re-read the known-corrupt head.
    assert store.newest() is None
    assert metrics.series_sum("spill_crc_failures_total") == failures
    # A new publish moves the head and re-enables the load path.
    store.publish(2, {"w": 2})
    assert store.newest() == (2, {"w": 2})


def test_elect_newest_version_wins_ties_to_lowest_rank():
    records = [{"rank": 0, "version": 1}, {"rank": 1, "version": 3},
               {"rank": 2, "version": 3}]
    assert elect_newest(records, keys=("version",))["rank"] == 1
    # No evidence anywhere degenerates to rank 0 (the reference's
    # rank-0 broadcast) — same rule elastic.state relies on.
    fresh = [{"rank": r} for r in (2, 0, 1)]
    assert elect_newest(fresh)["rank"] == 0
    # The hot-swap election: version outranks progress, progress
    # breaks version ties.
    mixed = [{"rank": 0, "version": 2, "commit_id": 9},
             {"rank": 1, "version": 3, "commit_id": 1},
             {"rank": 2, "version": 3, "commit_id": 4}]
    win = elect_newest(mixed, keys=("version", "commit_id"))
    assert win["rank"] == 2


# -- in-process replica set -------------------------------------------------

def test_replicaset_kill_requeues_and_survivors_elect_newest(tmp_path):
    metrics.reset()
    store = VersionStore(str(tmp_path))
    store.publish(1, {"version": 1})
    router = Router(max_batch_size=4, max_wait_us=1000)
    served_versions = []

    def model_fn(weights, payloads):
        served_versions.append(int(weights["version"]))
        time.sleep(0.01)
        return [p * 2 for p in payloads]

    rset = ReplicaSet("d", model_fn, router, store=store,
                      min_replicas=1, max_replicas=4).start(2)
    try:
        assert _wait_for(lambda: rset.ready_count() == 2)
        reqs = [router.submit("d", i) for i in range(8)]
        for r in reqs:
            assert r.wait(10.0)
        assert [r.outcome for r in reqs] == ["ok"] * 8
        assert rset.cold_start_seconds() is not None
        # Kill one replica mid-service and roll a new version: zero
        # requests lost, survivors converge on the NEWEST version.
        rset.kill(0)
        store.publish(2, {"version": 2})
        more = [router.submit("d", i) for i in range(8)]
        for r in more:
            assert r.wait(10.0)
        assert [r.outcome for r in more] == ["ok"] * 8
        assert [r.result for r in more] == [i * 2 for i in range(8)]
        assert _wait_for(lambda: rset.live_count() == 1)
        assert _wait_for(lambda: set(rset.versions()) == {2})
        assert rset.target_version() == 2
        ok = metrics.series_sum("serving_requests_total",
                                deployment="d", outcome="ok")
        assert ok == 16            # every request exactly once
    finally:
        rset.stop()


def test_replicaset_respawns_to_min_replicas_after_death():
    router = Router(max_batch_size=4, max_wait_us=1000)
    rset = ReplicaSet("d", lambda w, ps: [p + 1 for p in ps], router,
                      min_replicas=1, max_replicas=4).start(1)
    try:
        assert _wait_for(lambda: rset.ready_count() == 1)
        rset.kill(0)
        router.submit("d", 0)      # wakes the doomed replica
        # The sole replica died: the floor respawns a replacement and
        # the queue keeps draining instead of stranding forever.
        assert _wait_for(lambda: rset.ready_count() == 1, timeout=10)
        req = router.submit("d", 41)
        assert req.wait(10.0) and req.outcome == "ok"
        assert req.result == 42
    finally:
        rset.stop()


def test_replicaset_stop_leaves_shared_router_serving():
    router = Router(max_batch_size=4, max_wait_us=1000)
    rset_a = ReplicaSet("a", lambda w, ps: ps, router).start(1)
    rset_b = ReplicaSet("b", lambda w, ps: ps, router).start(1)
    try:
        assert _wait_for(lambda: rset_b.ready_count() == 1)
        rset_a.stop()
        # Decommissioning deployment A must not wedge deployment B's
        # pull loop on the SHARED router (one HTTP front door mounts
        # one router for every deployment).
        req = router.submit("b", "still-served")
        assert req.wait(10.0) and req.outcome == "ok"
    finally:
        rset_b.stop()


def test_replicaset_scale_down_finishes_in_flight_batch():
    router = Router(max_batch_size=2, max_wait_us=1000)
    rset = ReplicaSet("d", lambda w, ps: ps, router,
                      min_replicas=1, max_replicas=4).start(3)
    try:
        assert _wait_for(lambda: rset.ready_count() == 3)
        rset.scale(1)
        assert _wait_for(lambda: rset.live_count() == 1)
        req = router.submit("d", "x")
        assert req.wait(5.0) and req.outcome == "ok"
    finally:
        rset.stop()


# -- HTTP front door --------------------------------------------------------

def test_http_front_door_serves_authed_requests():
    from horovod_tpu.runner.http_server import (RendezvousServer,
                                                SECRET_HEADER,
                                                compute_digest)
    secret = "s3cret"
    server = RendezvousServer(host="127.0.0.1", secret=secret)
    port = server.start()
    router = Router(max_batch_size=4, max_wait_us=1000)
    rset = ReplicaSet("m", lambda w, ps: [p["x"] + 1 for p in ps],
                      router).start(1)
    try:
        url = "http://127.0.0.1:%d/serve/m" % port
        body = json.dumps({"x": 41, "timeout_s": 10}).encode()

        def post(payload, digest):
            req = urllib.request.Request(
                url, data=payload, method="POST",
                headers={SECRET_HEADER: digest})
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as err:
                return err.code, b""

        # No provider installed: this server is a rendezvous KV first.
        status, _ = post(body, compute_digest(secret, body))
        assert status == 404
        install_http_frontend(server, router)
        status, out = post(body, compute_digest(secret, body))
        assert status == 200
        reply = json.loads(out.decode())
        assert reply["outcome"] == "ok" and reply["result"] == 42
        # Same HMAC auth as the KV paths: a bad digest never reaches
        # the router.
        status, _ = post(body, "bogus")
        assert status == 403
        assert router.depth("m") == 0
    finally:
        rset.stop()
        server.stop()


# -- deployment-as-tenant + the resize/poke satellite fix -------------------

class _StubDriver:
    def __init__(self, tenant):
        self.tenant = tenant
        self.np_bounds = None
        self._stop = threading.Event()

    def run(self):
        self._stop.wait()
        return 0

    def set_np_bounds(self, min_np, max_np):
        self.np_bounds = (min_np, max_np)

    def scheduler_preempt(self, reason):
        pass

    def scheduler_resume(self):
        pass

    def request_stop(self):
        self._stop.set()

    def finish(self):
        self._stop.set()


def test_admit_deployment_maps_slo_to_priority():
    captured = {}

    class _Sched:
        def admit(self, spec):
            captured["spec"] = spec
            return RUNNING

    spec = DeploymentSpec("chat", ["serve"], slo_class=7,
                          min_replicas=2, max_replicas=6)
    tenant_id = admit_deployment(_Sched(), spec)
    assert tenant_id == "serve-chat"
    admitted = captured["spec"]
    assert admitted.tenant_id == "serve-chat"
    assert admitted.priority == 7
    # Start at the floor: growth is the autoscaler's call, not free
    # slack absorption.
    assert (admitted.min_np, admitted.max_np) == (2, 2)
    assert admitted.env["HOROVOD_SERVING_DEPLOYMENT"] == "chat"
    with pytest.raises(ValueError):
        DeploymentSpec("", ["serve"])


def test_scheduler_resize_validation():
    sched = PodScheduler(FixedHosts({"h1": 2}),
                         driver_factory=_StubDriver, tick_secs=30)
    with pytest.raises(KeyError):
        sched.resize("nope", max_np=2)
    try:
        sched.start()
        assert sched.admit(TenantSpec("A", ["true"], min_np=1,
                                      max_np=1)) == RUNNING
        with pytest.raises(ValueError):
            sched.resize("A", min_np=0)
        with pytest.raises(ValueError):
            sched.resize("A", min_np=3, max_np=2)
    finally:
        sched.stop(timeout=10)


def test_scheduler_poke_applies_resize_without_waiting_tick(tmp_path):
    """The satellite fix: with a LONG tick cadence, a resize alone
    waits for the next scheduled tick, but resize + poke() lands on an
    immediate replan."""
    sched = PodScheduler(FixedHosts({"h1": 2}),
                         driver_factory=_StubDriver, tick_secs=30)
    try:
        sched.start()
        assert sched.admit(TenantSpec("A", ["true"], min_np=1,
                                      max_np=1)) == RUNNING
        assert _wait_for(
            lambda: sched._tenants["A"].allocated() == 1)
        time.sleep(0.5)   # drain admit()'s own wake-up of the loop
        sched.resize("A", max_np=2)
        time.sleep(0.5)
        # No poke: the 30 s cadence hasn't replanned yet.
        assert sched._tenants["A"].allocated() == 1
        sched.poke()
        assert _wait_for(
            lambda: sched._tenants["A"].allocated() == 2,
            timeout=5.0), "poke() must trigger an immediate replan"
        # The live driver's own np bounds moved too — without this a
        # real tenant would keep truncating its world at the
        # admission-time max_np and the widened view could never be
        # taken up (the serving scale-up's convergence bug).
        assert sched._tenants["A"].driver.np_bounds == (1, 2)
    finally:
        sched.stop(timeout=10)


def test_tenant_autoscaler_orders_land_via_resize_and_poke():
    calls = []

    class _Sched:
        def tenant_driver(self, tid):
            calls.append(("driver", tid))
            return None

        def resize(self, tid, min_np=None, max_np=None):
            calls.append(("resize", tid, max_np))

        def poke(self):
            calls.append(("poke",))

    spec = DeploymentSpec("m", ["serve"], min_replicas=1,
                          max_replicas=4)
    scaler = tenant_autoscaler(_Sched(), "serve-m", spec,
                               depth_fn=lambda: 12.0, interval=60,
                               cooldown=0.0, up_qdepth=4.0,
                               down_qdepth=0.5)
    scaler.tick()
    assert ("resize", "serve-m", 3) in calls
    assert calls[-1] == ("poke",)  # applied next tick, not next cadence


# -- real-process e2es (slow; CI `serving-smoke` runs them by node id) ------

SERVING_WORKER = """
import os, sys, time
import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.serving import FileWorkQueue, VersionStore, \
    serve_from_queue

hvd.init()
state = elastic.ObjectState(version=0, weights=None)
queue = FileWorkQueue(os.environ["SERVE_QUEUE_DIR"])
store = VersionStore(os.environ["SERVE_STORE_DIR"])

def note(line):
    with open(os.environ["SERVE_LOG"], "a") as f:
        f.write(line + "\\n")

def handler(req_id, payload):
    time.sleep(float(os.environ.get("SERVE_STEP_SECS", "0.05")))
    return {"y": payload["x"] * 2, "version": state.version}

@elastic.run
def serve(state):
    note("ENTER version=%d commit=%d" % (state.version,
                                         state._commit_id))
    serve_from_queue(queue, handler, state=state, store=store,
                     deployment=os.environ.get(
                         "HOROVOD_SERVING_DEPLOYMENT", "m"),
                     total=int(os.environ["SERVE_TOTAL"]))
    note("DONE version=%d" % state.version)

serve(state)
"""


def _serving_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_ELASTIC_DRIVER_ADDR", None)
    env.update(extra or {})
    return env


def _lines(path):
    try:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


@pytest.mark.slow
def test_serving_hot_swap_under_replica_die_e2e(tmp_path):
    """ISSUE 11 acceptance: a 2-replica process deployment serves a
    stream while a new model version rolls across it AND one replica
    is killed mid-service (``serving.replica.die`` injection).  Zero
    requests lost — the dead replica's claims are swept back and
    served by the survivor — and the re-formed group converges on the
    NEWEST version (the swap's commit is the election evidence)."""
    total = 24
    queue = FileWorkQueue(str(tmp_path / "q"))
    store = VersionStore(str(tmp_path / "versions"))
    store.publish(1, {"version": 1})
    log = tmp_path / "serve.log"
    base = _serving_env({
        # Die on the slot-1 replica's SECOND claimed batch, epoch 1
        # only (the respawn runs in epoch 2 and serves on).
        "HVD_TPU_FAULT":
            "serving.replica.die:die:43@slot=1@epoch=1@after=1",
        "HOROVOD_SERVING_MAX_BATCH": "2",
    })
    sched = PodScheduler(FixedHosts({"127.0.0.1": 2}), env=base,
                         tick_secs=0.2, failure_threshold=10,
                         start_timeout=60)
    script = tmp_path / "serve.py"
    script.write_text(SERVING_WORKER)
    spec = DeploymentSpec(
        "m", [sys.executable, str(script)], slo_class=5, min_replicas=2,
        env={"SERVE_QUEUE_DIR": str(tmp_path / "q"),
             "SERVE_STORE_DIR": str(tmp_path / "versions"),
             "SERVE_LOG": str(log), "SERVE_TOTAL": str(total)})
    try:
        sched.start()
        tenant_id = admit_deployment(sched, spec)
        assert tenant_id == "serve-m"
        assert sched.tenant_state(tenant_id) == RUNNING
        ids = []
        for i in range(total):
            ids.append(queue.submit({"x": i}, req_id="r%03d" % i))
            time.sleep(0.02)
            if i == total // 3:
                # Roll the new version mid-stream.
                store.publish(2, {"version": 2})
        assert _wait_for(lambda: sched.tenant_state(tenant_id) == DONE,
                         timeout=240, interval=0.25), (
            "tenant=%s log=%r" % (sched.tenant_state(tenant_id),
                                  _lines(log)))
        # Zero requests lost, every answer exact, each served once.
        assert queue.done_count() == total
        for i, rid in enumerate(ids):
            result = queue.result(rid)
            assert result is not None and result["y"] == i * 2, (
                rid, result)
        lines = _lines(log)
        # The injection really fired: the killed replica's world
        # re-formed (>= 3 ENTERs: 2 initial + >= 1 re-rendezvous).
        assert len([l for l in lines if l.startswith("ENTER")]) >= 3, \
            lines
        assert metrics.series_sum("elastic_worker_failures_total",
                                  tenant=tenant_id) >= 1
        # Survivors elected the newest version: every replica finished
        # AT version 2, and post-roll traffic was served by v2.
        dones = [l for l in lines if l.startswith("DONE")]
        assert dones and all(l == "DONE version=2" for l in dones), \
            lines
        assert queue.result(ids[-1])["version"] == 2
    finally:
        sched.stop(timeout=30)


@pytest.mark.slow
def test_serving_tenant_autoscale_e2e(tmp_path):
    """Traffic-driven autoscaling through the REAL planes: a burst
    builds queue depth, the autoscaler orders a grow, the order lands
    via ``scheduler.resize`` + ``poke`` (next tick, not next cadence),
    the elastic driver spawns the second replica, and the deployment
    drains the backlog with zero lost requests."""
    total = 40
    queue = FileWorkQueue(str(tmp_path / "q"))
    store = VersionStore(str(tmp_path / "versions"))
    store.publish(1, {"version": 1})
    log = tmp_path / "serve.log"
    base = _serving_env({"HOROVOD_SERVING_MAX_BATCH": "2"})
    sched = PodScheduler(FixedHosts({"127.0.0.1": 2}), env=base,
                         tick_secs=0.2, start_timeout=60)
    script = tmp_path / "serve.py"
    script.write_text(SERVING_WORKER)
    spec = DeploymentSpec(
        "m", [sys.executable, str(script)], min_replicas=1,
        max_replicas=2,
        env={"SERVE_QUEUE_DIR": str(tmp_path / "q"),
             "SERVE_STORE_DIR": str(tmp_path / "versions"),
             "SERVE_LOG": str(log), "SERVE_TOTAL": str(total),
             # Slow enough that the backlog outlasts replica 2's cold
             # start — the grow order must land and CONVERGE mid-run.
             "SERVE_STEP_SECS": "0.3"})
    scaler = None
    try:
        sched.start()
        tenant_id = admit_deployment(sched, spec)
        assert sched.tenant_state(tenant_id) == RUNNING
        scaler = tenant_autoscaler(
            sched, tenant_id, spec, depth_fn=queue.depth,
            interval=0.2, cooldown=600,   # no shrink mid-run
            up_qdepth=4.0, down_qdepth=0.5)
        ids = [queue.submit({"x": i}, req_id="r%03d" % i)
               for i in range(total)]
        scaler.start()
        driver = sched.tenant_driver(tenant_id)
        assert driver is not None
        # The burst drives a grow order and the order CONVERGES: a
        # second real worker process comes up and takes traffic.
        assert _wait_for(lambda: driver.live_worker_count() == 2,
                         timeout=120, interval=0.25), (
            scaler.decisions, _lines(log))
        assert any(d["to"] == 2 for d in scaler.decisions)
        assert _wait_for(lambda: scaler.last_scale_up_secs is not None,
                         timeout=30)
        assert _wait_for(lambda: sched.tenant_state(tenant_id) == DONE,
                         timeout=240, interval=0.25), (
            "tenant=%s log=%r" % (sched.tenant_state(tenant_id),
                                  _lines(log)))
        assert queue.done_count() == total
        for i, rid in enumerate(ids):
            result = queue.result(rid)
            assert result is not None and result["y"] == i * 2
        # Both replicas really served (two ENTER lines, two DONEs).
        lines = _lines(log)
        assert len([l for l in lines if l.startswith("ENTER")]) >= 2
    finally:
        if scaler is not None:
            scaler.stop()
        sched.stop(timeout=30)
