"""Sharded durable commits (ISSUE 15): flat layout, manifest + shard
blobs, N→M range streaming, per-shard/per-commit fallback, the
``elastic.state.shard`` injection, and the state.py wiring — fast
units (no spawned processes; the 2-proc e2es live in
test_elastic.py)."""

import os

import numpy as np
import pytest

from horovod_tpu.common import faultline, metrics
from horovod_tpu.elastic import shardspill, spill
from horovod_tpu.elastic.state import JaxState


def _payload(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "attrs": {"epoch": 3, "batch": 7},
        "trees": {
            "params": {"w": rng.randn(16, 8).astype(np.float32),
                       "b": rng.randn(8).astype(np.float64)},
            "opt": (np.int32(4),
                    {"mu": rng.randn(2, 3).astype(np.float32)}),
        },
    }


def _assert_payload_equal(a, b):
    assert a["attrs"] == b["attrs"]
    np.testing.assert_array_equal(a["trees"]["params"]["w"],
                                  b["trees"]["params"]["w"])
    np.testing.assert_array_equal(a["trees"]["params"]["b"],
                                  b["trees"]["params"]["b"])
    np.testing.assert_array_equal(a["trees"]["opt"][1]["mu"],
                                  b["trees"]["opt"][1]["mu"])


def _write_world(commit, buf, layout, d, n=2):
    for r in range(n):
        assert shardspill.write_commit(commit, buf, layout,
                                       shard_index=r, n_shards=n,
                                       tag="r%d" % r, d=str(d))


def _tear(path, keep_frac=0.5):
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:int(len(blob) * keep_frac)])


def test_flatten_unflatten_roundtrip_mixed_trees():
    payload = _payload()
    buf, layout = shardspill.flatten_state(payload)
    assert layout[0]["key"] == "__head__"
    # every tree leaf appears at a recorded range with dtype/shape
    keys = [e["key"] for e in layout[1:]]
    assert len(keys) == 4 and all(k.startswith("t:") for k in keys)
    assert layout[-1]["offset"] + layout[-1]["nbytes"] == len(buf)
    _assert_payload_equal(shardspill.unflatten_state(buf, layout),
                          payload)


def test_shard_range_partitions_exactly():
    for total in (0, 1, 7, 100):
        for n in (1, 2, 3, 7):
            ranges = [shardspill.shard_range(total, n, i)
                      for i in range(n)]
            assert ranges[0][0] == 0 and ranges[-1][1] == total
            for (a, b), (c, _d) in zip(ranges, ranges[1:]):
                assert b == c and a <= b


def test_write_scan_restore_roundtrip_and_replicas(tmp_path):
    buf, layout = shardspill.flatten_state(_payload())
    _write_world(9, buf, layout, tmp_path)
    names = sorted(os.listdir(tmp_path))
    # 2 manifests + each shard index has its own copy AND one buddy
    assert sum(n.endswith(".manifest") for n in names) == 2
    assert sum(n.endswith(".shard") for n in names) == 4
    assert shardspill.have_evidence(str(tmp_path))
    assert shardspill.newest_manifest_commit(str(tmp_path)) == 9
    cid, restored = shardspill.restore_local(d=str(tmp_path))
    assert cid == 9
    _assert_payload_equal(restored, _payload())


def test_n_to_m_range_streaming_bitwise(tmp_path):
    buf, layout = shardspill.flatten_state(_payload(1))
    _write_world(5, buf, layout, tmp_path, n=2)
    manifest = shardspill.load_manifest(5, d=str(tmp_path))
    assert manifest["n_shards"] == 2
    for m in (1, 3, 5):
        chunks = [shardspill.read_range(
            manifest, *shardspill.shard_range(len(buf), m, j),
            d=str(tmp_path)) for j in range(m)]
        assert b"".join(chunks) == buf, "M=%d reassembly differs" % m


def test_reader_streams_less_than_full_state(tmp_path):
    """The N→M claim at unit level: one reader of an M=3 world reads
    only the source shards overlapping its range — strictly less than
    the full stream."""
    buf, layout = shardspill.flatten_state(_payload(2))
    _write_world(5, buf, layout, tmp_path, n=2)
    manifest = shardspill.load_manifest(5, d=str(tmp_path))
    before = metrics.series_sum("shardspill_restore_bytes_total")
    lo, hi = shardspill.shard_range(len(buf), 3, 0)
    shardspill.read_range(manifest, lo, hi, d=str(tmp_path))
    streamed = metrics.series_sum("shardspill_restore_bytes_total") \
        - before
    assert 0 < streamed < len(buf), (streamed, len(buf))


def test_corrupt_copy_falls_back_per_shard_not_per_commit(tmp_path):
    buf, layout = shardspill.flatten_state(_payload(3))
    _write_world(9, buf, layout, tmp_path)
    _tear(tmp_path / ("shard-%020d-0of2-r0.shard" % 9))
    before = metrics.series_sum("shardspill_shard_fallbacks_total")
    cid, restored = shardspill.restore_local(d=str(tmp_path))
    assert cid == 9  # the commit survives the torn copy
    _assert_payload_equal(restored, _payload(3))
    assert metrics.series_sum("shardspill_shard_fallbacks_total") \
        == before + 1


def test_all_copies_corrupt_falls_back_per_commit(tmp_path):
    buf, layout = shardspill.flatten_state(_payload(4))
    _write_world(8, buf, layout, tmp_path)
    _write_world(9, buf, layout, tmp_path)
    for r in range(2):
        _tear(tmp_path / ("shard-%020d-0of2-r%d.shard" % (9, r)))
    cid, _restored = shardspill.restore_local(d=str(tmp_path))
    assert cid == 8  # every copy of commit 9's shard 0 is bad


def test_prune_keeps_last_k_commits(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_STATE_KEEP", "2")
    buf, layout = shardspill.flatten_state(_payload())
    for commit in (1, 2, 3, 4):
        _write_world(commit, buf, layout, tmp_path)
    names = os.listdir(tmp_path)
    assert not any("%020d" % 1 in n for n in names), names
    assert not any("%020d" % 2 in n for n in names), names
    cid, _ = shardspill.restore_local(d=str(tmp_path))
    assert cid == 4


def test_shard_cond_key_parses_and_targets_one_index():
    specs = faultline.parse("elastic.state.shard:drop@shard=1")
    spec = specs["elastic.state.shard"]
    assert spec.action == "drop" and spec.conds == (("shard", "1"),)


def test_torn_shard_injection_buddy_survives(tmp_path, monkeypatch):
    """elastic.state.shard@shard=1@times=1 tears exactly the FIRST
    copy of shard 1 this process writes; the buddy copy lands intact
    and restore stays at the commit (per-shard fallback, commit not
    discarded)."""
    monkeypatch.setenv("HVD_TPU_FAULT",
                       "elastic.state.shard:drop@shard=1@times=1")
    faultline.reset()
    buf, layout = shardspill.flatten_state(_payload(5))
    try:
        _write_world(7, buf, layout, tmp_path)
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
    cid, restored = shardspill.restore_local(d=str(tmp_path))
    assert cid == 7
    _assert_payload_equal(restored, _payload(5))


def test_torn_all_copies_discards_commit(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT", "elastic.state.shard:drop@shard=1")
    faultline.reset()
    buf, layout = shardspill.flatten_state(_payload(6))
    try:
        _write_world(7, buf, layout, tmp_path)
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT")
        faultline.reset()
    assert shardspill.restore_local(d=str(tmp_path)) is None
    assert shardspill.have_evidence(str(tmp_path))


# -- state.py wiring --------------------------------------------------------

def _fake_world(monkeypatch, rank, size):
    from horovod_tpu.common import basics
    monkeypatch.setattr(basics, "is_initialized", lambda: True)
    monkeypatch.setattr(basics, "rank", lambda: rank)
    monkeypatch.setattr(basics, "size", lambda: size)
    monkeypatch.setattr(basics, "_controller_is_spmd", lambda: False)


def test_jax_state_sharded_commit_and_local_restore(tmp_path,
                                                    monkeypatch):
    """JaxState with HOROVOD_STATE_SHARD_SPILL=1 in a (faked) 2-rank
    world spills manifest + shard blobs; a later single-rank world
    (the 2→1 resize) restores the exact trees through the sharded
    local path."""
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STATE_SHARD_SPILL", "1")
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    for rank in range(2):
        _fake_world(monkeypatch, rank, 2)
        state = JaxState(params={k: v.copy() for k, v in params.items()},
                         batch=5)
        state._commit_id = 3
        state.save()
        state._persist()
    names = os.listdir(tmp_path)
    assert any(n.endswith(".manifest") for n in names), names
    assert any(n.endswith(".shard") for n in names), names
    assert not any(n.endswith(".spill") for n in names), names

    from horovod_tpu.common import basics
    monkeypatch.setattr(basics, "is_initialized", lambda: False)
    fresh = JaxState(params={k: np.zeros_like(v)
                             for k, v in params.items()}, batch=0)
    fresh.sync()
    assert fresh._commit_id == 3 and fresh.batch == 5
    np.testing.assert_array_equal(np.asarray(fresh.params["w"]),
                                  params["w"])


def test_sharded_evidence_refuses_blank_restart(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STATE_SHARD_SPILL", "1")
    buf, layout = shardspill.flatten_state(_payload())
    _write_world(4, buf, layout, tmp_path)
    for name in os.listdir(tmp_path):
        if name.endswith(".shard"):
            _tear(tmp_path / name, 0.3)
    from horovod_tpu.elastic.state import StateSyncError
    state = JaxState(params={"w": np.zeros(3, np.float32)}, batch=0)
    with pytest.raises(StateSyncError):
        state.sync()


# -- spill.scan satellite ---------------------------------------------------

def test_scan_skips_empty_tag_filenames_with_one_warning(tmp_path,
                                                         caplog):
    good = spill.encode(5, b"payload")
    (tmp_path / ("state-%020d-r0.spill" % 5)).write_bytes(good)
    # Hand-renamed: commit id parses, tag segment empty.
    (tmp_path / ("state-%020d-.spill" % 7)).write_bytes(
        spill.encode(7, b"rogue"))
    spill._scan_warned.clear()
    with caplog.at_level("WARNING",
                         logger="horovod_tpu.elastic.spill"):
        out = spill.scan(str(tmp_path))
        out2 = spill.scan(str(tmp_path))
    assert [c for c, _ in out] == [5] and out == out2
    warned = [r for r in caplog.records
              if "writer-tag segment is empty" in r.getMessage()]
    assert len(warned) == 1, "one warning per filename, not per poll"


def test_read_shards_round_robin_reassembles(tmp_path):
    """The collective restore's ownership unit: readers j of M own
    source shards s % M == j; the union reassembles the stream and no
    reader touches more than ceil(N/M) shards."""
    buf, layout = shardspill.flatten_state(_payload(8))
    _write_world(5, buf, layout, tmp_path, n=2)
    manifest = shardspill.load_manifest(5, d=str(tmp_path))
    for m in (1, 2, 3):
        merged = {}
        for j in range(m):
            mine = [s for s in range(2) if s % m == j]
            assert len(mine) <= -(-2 // m)
            merged.update(shardspill.read_shards(manifest, mine,
                                                 d=str(tmp_path)))
        assert b"".join(merged[s] for s in range(2)) == buf, m


def test_flag_rollback_still_restores_sharded_files(tmp_path,
                                                    monkeypatch):
    """Review regression: sharded files count as durable evidence
    regardless of HOROVOD_STATE_SHARD_SPILL, so restore must be
    reachable for them with the flag OFF too — a flag rollback must
    not turn valid commits into a permanently refused restart."""
    monkeypatch.setenv("HOROVOD_STATE_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STATE_SHARD_SPILL", "1")
    params = {"w": np.arange(6, dtype=np.float32)}
    for rank in range(2):
        _fake_world(monkeypatch, rank, 2)
        state = JaxState(params={k: v.copy() for k, v in params.items()},
                         batch=2)
        state._commit_id = 4
        state.save()
        state._persist()
    monkeypatch.delenv("HOROVOD_STATE_SHARD_SPILL")
    from horovod_tpu.common import basics
    monkeypatch.setattr(basics, "is_initialized", lambda: False)
    fresh = JaxState(params={"w": np.zeros(6, np.float32)}, batch=0)
    fresh.sync()
    assert fresh._commit_id == 4 and fresh.batch == 2
    np.testing.assert_array_equal(np.asarray(fresh.params["w"]),
                                  params["w"])
