"""Skew observatory tests (ISSUE 12): the observe→decide→act loop.

Fast units drive synthetic fleet snapshots through the analyzer /
observatory / staleness tracker and the plancache actuation seams; the
slow-marked e2e closes the real loop — an injected dispatch-seam delay
on one host of a live elastic multihost world must produce a
``straggler_detected`` event, a drain actuation through the r10
planned-removal path, and a recovered world.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from horovod_tpu.common import metrics, skew
from tests.utils.spawn import scaled_timeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


def _model(lat_sum, lat_count, qdepth=None, group=None):
    """A minimal snapshot model with cumulative mh_collective_seconds
    totals (what one worker's pull returns)."""
    model = {"mh_collective_seconds": {
        "kind": "histogram", "help": "",
        "series": [{"labels": {"op": "allreduce",
                               "size_class": "65536"},
                    "buckets": {}, "sum": lat_sum,
                    "count": lat_count}]}}
    if qdepth is not None:
        model["engine_queue_depth"] = {
            "kind": "gauge", "help": "",
            "series": [{"labels": {}, "value": qdepth}]}
    if group is not None:
        model["engine_last_group_id"] = {
            "kind": "gauge", "help": "",
            "series": [{"labels": {}, "value": group}]}
    return model


def _feed(target, ticks, dt=0.5, per_tick=4, slow=0.05, fast=0.001,
          start=0.0, now0=0.0):
    """Feed ``ticks`` observation passes where rank 1 is the DELAYED
    rank: its own latency is the fleet minimum (``fast``) while rank
    0's inflates by the wait (``slow``) — the arrival-lag inversion.
    Returns the last scores dict."""
    out = {}
    for i in range(1, ticks + 1):
        n = per_tick * i
        models = [("0", ("h0", 0), _model(start + slow * n, n,
                                          qdepth=1, group=n)),
                  ("1", ("h1", 0), _model(start + fast * n, n,
                                          qdepth=0, group=n))]
        out = target.observe(models, now=now0 + dt * i)
    return out


# -- analyzer ---------------------------------------------------------------

def test_analyzer_fingers_the_late_arriver():
    an = skew.SkewAnalyzer(window_secs=2.0)
    scores = _feed(an, ticks=5)
    # Rank 1 dispatches late (everyone waits on it): its own window is
    # the fleet minimum, so ITS score spikes — not the prompt rank's.
    assert scores["1"]["score"] > 10.0, scores
    assert scores["0"]["score"] < 1.0, scores
    assert scores["1"]["queue_depth"] == 0.0
    assert scores["1"]["last_group_id"] == 20.0


def test_analyzer_needs_two_ranks_and_window_data():
    an = skew.SkewAnalyzer(window_secs=2.0)
    # One rank: no median to compare against.
    assert an.observe([("0", None, _model(0.1, 10))], now=0.0) == {}
    assert an.observe([("0", None, _model(0.2, 20))], now=1.0) == {}
    # Two ranks but below MIN_WINDOW_COUNT completions: no scores yet.
    out = an.observe([("0", None, _model(0.21, 21)),
                      ("1", None, _model(0.01, 1))], now=1.5)
    assert "1" not in out


def test_analyzer_drops_departed_ranks():
    an = skew.SkewAnalyzer(window_secs=2.0)
    _feed(an, ticks=3)
    assert an.rank_window("1") is not None
    # Rank 1 left the fleet (drained): its window must reset so a
    # respawn starts a fresh episode.
    an.observe([("0", None, _model(1.0, 20))], now=2.0)
    assert an.rank_window("1") is None


def test_analyzer_falls_back_to_cycle_seconds():
    an = skew.SkewAnalyzer(window_secs=2.0)

    def cyc(lat_sum, count):
        return {"engine_cycle_seconds": {
            "kind": "histogram", "help": "",
            "series": [{"labels": {}, "buckets": {}, "sum": lat_sum,
                        "count": count}]}}

    for i in range(1, 5):
        n = 4 * i
        out = an.observe([("0", None, cyc(0.05 * n, n)),
                          ("1", None, cyc(0.001 * n, n))],
                         now=0.5 * i)
    assert an.source == "engine_cycle_seconds"
    assert out["1"]["score"] > 10.0


# -- env knobs --------------------------------------------------------------

def test_action_env_is_strict(monkeypatch):
    monkeypatch.setenv("HOROVOD_STRAGGLER_ACTION", "Drain")
    assert skew.straggler_action() == "drain"
    monkeypatch.setenv("HOROVOD_STRAGGLER_ACTION", "observe-ish")
    with pytest.raises(ValueError):
        skew.straggler_action()
    monkeypatch.delenv("HOROVOD_STRAGGLER_ACTION")
    assert skew.straggler_action() == "observe"


def test_threshold_and_window_envs(monkeypatch):
    monkeypatch.setenv("HOROVOD_STRAGGLER_THRESHOLD", "0")
    assert skew.straggler_threshold() == 0.0
    monkeypatch.setenv("HOROVOD_STRAGGLER_WINDOW_SECS", "0.01")
    assert skew.straggler_window_secs() == 0.5  # floor
    monkeypatch.setenv("HOROVOD_PLAN_STALENESS_RATIO", "3.5")
    assert skew.plan_staleness_ratio() == 3.5


# -- observatory: sustained detection + actuation ---------------------------

def test_detection_requires_sustained_skew(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    drained = []
    obs = skew.SkewObservatory(threshold=2.0, window_secs=2.0,
                               action="drain",
                               drain_fn=lambda meta: bool(
                                   drained.append(meta)) or True)
    # 3 ticks x 0.5 s: above threshold but not yet sustained 2 s.
    _feed(obs, ticks=3)
    assert drained == []
    assert metrics.series_sum("straggler_detections_total") == 0
    # Scores published from the first complete window regardless.
    assert metrics.gauge("straggler_score", rank="1").value > 10
    # Two more ticks pass the sustained window: exactly one detection,
    # actuated and latched (further ticks must not re-fire).
    _feed(obs, ticks=8)
    assert drained == [("h1", 0)]
    assert metrics.series_sum("straggler_detections_total",
                              rank="1", action="drain") == 1
    _feed(obs, ticks=10)
    assert len(drained) == 1
    events = [r for r in metrics.iter_events(str(tmp_path))
              if r["kind"] == "straggler_detected"]
    assert len(events) == 1
    assert events[0]["rank"] == "1" and events[0]["action"] == "drain"
    assert events[0]["group"] is not None  # timeline correlation


def test_threshold_zero_disables_detection():
    obs = skew.SkewObservatory(threshold=0.0, window_secs=0.5,
                               action="drain",
                               drain_fn=lambda meta: True)
    _feed(obs, ticks=10)
    assert metrics.series_sum("straggler_detections_total") == 0
    # Scores still publish: /skew stays useful with detection off.
    assert metrics.gauge("straggler_score", rank="1").value > 10


def test_shrink_without_scheduler_observes(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    obs = skew.SkewObservatory(threshold=2.0, window_secs=1.0,
                               action="shrink", shrink_fn=None)
    _feed(obs, ticks=8)
    assert obs.describe()["detections"][0]["outcome"] == "observed"


def test_shrink_routes_through_callback_and_can_escalate():
    orders = []
    obs = skew.SkewObservatory(threshold=2.0, window_secs=1.0,
                               action="shrink",
                               shrink_fn=lambda meta: bool(
                                   orders.append(meta)) or True)
    _feed(obs, ticks=8)
    # A shed is a preference, not a guarantee: after a successful
    # shrink the episode RE-ARMS, so a wedged rank that survived the
    # placement change is shed again after another full sustained
    # window (two detections across these 8 half-second ticks).
    assert orders and all(meta == ("h1", 0) for meta in orders)
    assert len(orders) == 2, orders
    assert obs.describe()["detections"][0]["outcome"] == "shrunk"


def test_describe_schema_and_skew_endpoint():
    from horovod_tpu.runner.http_server import RendezvousServer
    obs = skew.SkewObservatory(threshold=2.0, window_secs=2.0,
                               action="observe")
    _feed(obs, ticks=8)
    server = RendezvousServer(secret="sekrit")
    port = server.start()
    try:
        # No provider installed: 404 (this server is a KV first).
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://127.0.0.1:%d/skew" % port, timeout=5)
        server.skew_provider = lambda: json.dumps(obs.describe(),
                                                  default=str)
        # Unauthenticated, like /metrics: read-only telemetry.
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/skew" % port, timeout=5).read()
    finally:
        server.stop()
    doc = json.loads(body)
    assert doc["threshold"] == 2.0
    assert doc["source"] == "mh_collective_seconds"
    assert doc["ranks"]["1"]["score"] > 10
    assert doc["ranks"]["1"]["above_threshold"] is True
    assert doc["detections"][0]["rank"] == "1"
    assert "staleness_ratio" in doc["plan"]


# -- plan-staleness tracking -------------------------------------------------

def test_class_tracker_baseline_then_trip_once():
    tr = skew.ClassLatencyTracker(ratio=2.0, min_count=3)
    key = ("allreduce", "65536")

    def feed(total, count):
        return tr.update({key: (total, count)})

    assert feed(0.004, 4) is None          # first sight
    assert feed(0.008, 8) is None          # baseline = 1 ms
    assert feed(0.012, 12) is None         # healthy
    trip = feed(0.212, 16)                 # 50 ms/op: 50x drift
    assert trip is not None and trip["op"] == "allreduce"
    assert trip["ratio"] > 2.0
    # Re-baselined at the drifted mean: the SAME level cannot re-trip.
    assert feed(0.412, 20) is None
    assert tr.describe()["allreduce/65536"]["stale_trips"] == 1


def test_class_tracker_one_class_per_pass():
    tr = skew.ClassLatencyTracker(ratio=2.0, min_count=2)
    a, b = ("allreduce", "1024"), ("allgather", "4096")
    tr.update({a: (0.002, 2), b: (0.002, 2)})
    tr.update({a: (0.004, 4), b: (0.004, 4)})       # baselines
    trip = tr.update({a: (0.104, 6), b: (0.024, 6)})  # a drifts worse
    assert (trip["op"], trip["size_class"]) == a
    # b's (smaller) drift trips on the NEXT pass — one class at a time.
    trip2 = tr.update({a: (0.204, 8), b: (0.044, 8)})
    assert (trip2["op"], trip2["size_class"]) == b


def test_observatory_plan_staleness_counts_and_journals(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_DIR", str(tmp_path))
    obs = skew.SkewObservatory(threshold=0.0, window_secs=2.0,
                               action="observe", staleness_ratio=2.0)
    # Healthy fleet (1 ms/op on both ranks), then every rank's class
    # latency drifts 50x — cumulative totals keep growing, as a real
    # pull stream's do.
    total, n = 0.0, 0
    for i in range(1, 13):
        per_op = 0.001 if i <= 6 else 0.05
        n += 4
        total += per_op * 4
        obs.observe([("0", None, _model(total, n)),
                     ("1", None, _model(total, n))], now=0.5 * i)
    # The fleet-view trip journals and shows in /skew; the
    # plan_staleness_total COUNTER belongs to the worker-side
    # actuation alone (check_plan_staleness) — a driver-side bump
    # would double-count one shift against a trip that invalidates
    # nothing.
    assert metrics.series_sum("plan_staleness_total") == 0
    events = [r for r in metrics.iter_events(str(tmp_path))
              if r["kind"] == "plan_stale"]
    assert len(events) == 1 and events[0]["size_class"] == "65536"
    assert events[0]["scope"] == "fleet"
    classes = obs.describe()["plan"]["classes"]
    assert classes["allreduce/65536"]["stale_trips"] == 1


def test_class_tracker_resets_on_total_regression():
    # Fleet-aggregated cumulative totals REGRESS when a member leaves
    # (its lifetime sums drop out of the aggregate): the tracker must
    # start the class over — never freeze until counts regrow, never
    # adopt a clamped 0-mean window as a baseline (the false-trip
    # shape).
    tr = skew.ClassLatencyTracker(ratio=2.0, min_count=3)
    key = ("allreduce", "65536")
    tr.update({key: (0.004, 4)})
    tr.update({key: (0.008, 8)})            # baseline 1 ms
    # A 2x-sized fleet member drained: totals drop below the last
    # sample.  No trip, no frozen window — a clean restart.
    assert tr.update({key: (0.002, 2)}) is None
    rec = tr.describe()["allreduce/65536"]
    assert rec["baseline_s"] is None and rec["stale_trips"] == 0
    # Tracking resumes from the fresh baseline and still detects real
    # drift afterwards.
    assert tr.update({key: (0.006, 6)}) is None   # new baseline 1 ms
    assert tr.update({key: (0.206, 10)}) is not None  # 50 ms: trip


def test_departed_rank_score_gauge_is_removed(tmp_path):
    obs = skew.SkewObservatory(threshold=0.0, window_secs=2.0,
                               action="observe")
    _feed(obs, ticks=5)
    assert metrics.series_sum("straggler_score", rank="1") > 10
    # Rank 1 leaves the fleet (drained): its gauge series must leave
    # the exposition with it, not report its last score forever.
    obs.observe([("0", ("h0", 0), _model(2.0, 40))], now=10.0)
    fam = metrics.snapshot().get("straggler_score", {})
    ranks = {row["labels"].get("rank") for row in fam.get("series", ())}
    assert "1" not in ranks, ranks


# -- plancache actuation -----------------------------------------------------

def _controller_with_entry():
    from horovod_tpu.utils import plancache
    plan = plancache.empty_plan("p2-l1-cpu")
    plan["collectives"] = {"allreduce": {"65536": {
        "path": "hier", "codec": "none"}}}
    return plancache.PlanController("p2-l1-cpu", plan, "cache", "none",
                                    hier_available=True,
                                    env_pinned=False)


def test_plan_controller_invalidate_drops_entry_and_memo():
    ctl = _controller_with_entry()
    assert ctl.route("allreduce", "65536", False) == (True, False)
    assert metrics.series_sum("plan_apply_total", source="cache") == 1
    assert ctl.invalidate("allreduce", "65536") is True
    # Re-resolves by the default gate, recounted with honest source.
    assert ctl.route("allreduce", "65536", False) == (False, True)
    assert metrics.series_sum("plan_apply_total", source="default") == 1
    assert ctl.invalidate("allreduce", "65536") is False  # nothing left


def _local_plane(monkeypatch, size=1, rank=None, kv=None):
    from horovod_tpu.utils import plancache
    plancache.reset()
    p = plancache._plane
    p.enabled = True
    p.fingerprint = "p2-l1-cpu"
    p.size = size
    p.rank = rank
    p.kv = kv
    p.controller = _controller_with_entry()
    return p


def test_check_plan_staleness_local_trips_exactly_once(monkeypatch):
    from horovod_tpu.utils import plancache
    p = _local_plane(monkeypatch)
    h = metrics.histogram("mh_collective_seconds", op="allreduce",
                          size_class="65536")

    def burst(lat, n=4):
        for _ in range(n):
            h.observe(lat)

    burst(0.001)
    assert plancache.check_plan_staleness() is None  # first sight
    burst(0.001)
    assert plancache.check_plan_staleness() is None  # baseline
    burst(0.05)
    v = plancache.check_plan_staleness()             # drift
    assert v is not None and v["size_class"] == "65536"
    assert metrics.series_sum("plan_staleness_total") == 1
    assert plancache.retune_pending() == [("allreduce", "65536")]
    # The cached routing entry is gone on trip.
    assert p.controller.route("allreduce", "65536", False) == (False,
                                                               True)
    burst(0.05)
    assert plancache.check_plan_staleness() is None  # re-baselined
    assert metrics.series_sum("plan_staleness_total") == 1
    assert plancache.consume_retune() == [("allreduce", "65536")]
    assert plancache.retune_pending() == []
    plancache.reset()


def test_check_plan_staleness_multi_without_kv_is_inert(monkeypatch):
    from horovod_tpu.utils import plancache
    _local_plane(monkeypatch, size=2, rank=0, kv=None)
    h = metrics.histogram("mh_collective_seconds", op="allreduce",
                          size_class="65536")
    for _ in range(16):
        h.observe(0.05)
    # Multi-member with no KV: rank-local invalidation would diverge
    # routing — the check must observe NOTHING, uniformly.
    for _ in range(4):
        assert plancache.check_plan_staleness() is None
    assert metrics.series_sum("plan_staleness_total") == 0
    plancache.reset()


def test_check_plan_staleness_member_adopts_rank0_verdict(monkeypatch):
    # The KV half of SPMD uniformity: rank 0 decides and publishes;
    # a member applies the trip at the SAME check index (apply_at),
    # never from its own telemetry (it has none here).
    from horovod_tpu.runner.http_client import RendezvousClient
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.utils import plancache
    server = RendezvousServer(secret="s3")
    server.start()
    try:
        kv = RendezvousClient("127.0.0.1:%d" % server.port, secret="s3")
        # rank 0: trip at its check #3, settle at #4.
        _local_plane(monkeypatch, size=2, rank=0, kv=kv)
        h = metrics.histogram("mh_collective_seconds", op="allreduce",
                              size_class="65536")
        vs = []
        for lat in (0.001, 0.001, 0.05, 0.05):
            for _ in range(4):
                h.observe(lat)
            vs.append(plancache.check_plan_staleness())
        assert vs[:2] == [None, None]
        assert vs[2] is not None and vs[2]["apply_at"] == 3
        assert vs[3] is None  # the settling window must not re-trip
        # member (rank 1): fresh process state, same KV.
        p = _local_plane(monkeypatch, size=2, rank=1, kv=kv)
        metrics.reset()
        assert plancache.check_plan_staleness() is None  # check 1
        assert plancache.check_plan_staleness() is None  # check 2
        v1 = plancache.check_plan_staleness()            # check 3
        assert v1 is not None
        assert (v1["op"], v1["size_class"], v1["apply_at"]) == \
            ("allreduce", "65536", 3)
        assert metrics.series_sum("plan_staleness_total") == 1
        assert plancache.retune_pending() == [("allreduce", "65536")]
        assert p.controller.route("allreduce", "65536", False) == \
            (False, True)
        assert plancache.check_plan_staleness() is None  # check 4
    finally:
        server.stop()
        plancache.reset()


# -- actuation seams ---------------------------------------------------------

class _FakeProc:
    def __init__(self):
        self.terminated = False

    def poll(self):
        return None

    def terminate(self):
        self.terminated = True


def test_driver_straggler_drain_is_planned_removal(monkeypatch):
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver
    driver = ElasticDriver(["true"], FixedHosts({"h1": 1}), min_np=1,
                           max_np=None)
    slot = ("h1", 0)
    mp = _FakeProc()
    with driver._lock:
        driver._procs[slot] = mp
        driver._spawn_backoff[slot] = 16.0
    assert driver._straggler_drain(slot) is True
    assert mp.terminated  # SIGTERM leads: the r10 drain path
    with driver._lock:
        assert slot in driver._draining        # reap books a drain
        assert slot not in driver._stopped     # the slot respawns
        assert slot not in driver._spawn_backoff  # backoff reset
    # Idempotent: an already-draining slot is not re-terminated.
    assert driver._straggler_drain(slot) is False
    # Unknown slots refuse quietly.
    assert driver._straggler_drain(("h9", 3)) is False


def test_scheduler_shrink_tenant_resizes_and_pokes():
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.scheduler import PodScheduler, TenantSpec

    class _FakeDriver:
        def __init__(self):
            self.bounds = []
            self.scheduler_shrink = None

        def set_np_bounds(self, lo, hi):
            self.bounds.append((lo, hi))

        def run(self):
            time.sleep(30)
            return 0

        def request_stop(self):
            pass

    fakes = {}

    def factory(tenant):
        fakes[tenant.tenant_id] = _FakeDriver()
        return fakes[tenant.tenant_id]

    sched = PodScheduler(FixedHosts({"h1": 3}), tick_secs=3600,
                         driver_factory=factory)
    try:
        sched.admit(TenantSpec("t1", ["true"], min_np=1, max_np=None))
        assert sched.tenant_state("t1") == "running"
        assert sum(sched.allocation("t1").values()) == 3
        # Shrink sheds ONE slot: max_np lands at allocated-1 and the
        # bound propagates to the live driver (resize + poke).
        assert sched.shrink_tenant("t1") is True
        assert fakes["t1"].bounds[-1] == (1, 2)
        sched.tick()
        assert sum(sched.allocation("t1").values()) == 2
        # At the min_np floor the shrink is refused.
        sched.resize("t1", max_np=1)
        sched.tick()
        assert sched.shrink_tenant("t1") is False
        # Unknown tenants refuse quietly.
        assert sched.shrink_tenant("nope") is False
    finally:
        sched.stop(timeout=2.0)


def test_scheduler_shrink_sheds_the_straggler_host():
    # The shed must land on the STRAGGLER's host, not an arbitrary
    # slot: shrink_tenant(host=...) records an avoid-host preference
    # the packer honors (that host fills LAST), so the tightened
    # max_np drops its slot.
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.scheduler import PodScheduler, TenantSpec

    class _FakeDriver:
        scheduler_shrink = None

        def set_np_bounds(self, lo, hi):
            pass

        def run(self):
            time.sleep(30)
            return 0

        def request_stop(self):
            pass

    sched = PodScheduler(FixedHosts({"h1": 2, "h2": 1}), tick_secs=3600,
                         driver_factory=lambda t: _FakeDriver())
    try:
        sched.admit(TenantSpec("t1", ["true"], min_np=1, max_np=None))
        assert sched.allocation("t1") == {"h1": 2, "h2": 1}
        # Straggler detected on h2: the shed must take h2's slot even
        # though host order would otherwise trim from the tail of h1.
        assert sched.shrink_tenant("t1", host="h2") is True
        sched.tick()
        assert sched.allocation("t1") == {"h1": 2}
    finally:
        sched.stop(timeout=2.0)


def test_scheduler_wires_shrink_hook_onto_tenant_drivers():
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.scheduler import (PodScheduler, TenantSpec,
                                               _Tenant)
    sched = PodScheduler(FixedHosts({"h1": 2}), tick_secs=3600)
    tenant = _Tenant(TenantSpec("t1", ["true"], min_np=1), 0)
    tenant.view.set({"h1": 2})
    with sched._lock:
        sched._tenants["t1"] = tenant
    driver = sched._make_driver(tenant)
    try:
        assert driver.scheduler_shrink is not None
        # The hook IS the observatory's shrink actuation path: one
        # call sheds one slot of this tenant's share.
        assert driver._straggler_shrink(("h1", 0)) is True
        assert tenant.spec.max_np == 1
    finally:
        driver.request_stop()


# -- e2e: detection -> drain -> recovery (slow; CI by node id) ---------------

@pytest.mark.slow
def test_straggler_detection_drain_recovery_e2e(tmp_path):
    """The whole loop on a real elastic multihost world: a dispatch-
    seam delay wedges one host (epoch 1 only), the driver's skew loop
    detects the sustained arrival lag, drains the straggler as a
    planned removal (no blacklist), and the re-formed world — with the
    straggler's healthy epoch-2 respawn — finishes every batch."""
    events_dir = tmp_path / "events"
    script = tmp_path / "train.py"
    script.write_text("""
import os, sys, time
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()
state = elastic.ObjectState(batch=0)

@elastic.run
def train(state):
    while state.batch < 40:
        hvd.allreduce(np.ones(256, np.float32), op=hvd.Sum,
                      name="b%d" % state.batch)
        state.batch += 1
        state.commit()
    print("DONE rank=%d size=%d batch=%d"
          % (hvd.rank(), hvd.size(), state.batch), flush=True)

train(state)
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_ELASTIC_DRIVER_ADDR", None)
    env.update({
        "HVD_TPU_FAULT":
            "mh.drain.record:delay:0.15@host=127.0.0.2@epoch=1",
        "HOROVOD_METRICS_DIR": str(events_dir),
        "HOROVOD_STRAGGLER_THRESHOLD": "2",
        "HOROVOD_STRAGGLER_WINDOW_SECS": "2",
        "HOROVOD_STRAGGLER_ACTION": "drain",
        # A real drain window (ManagedProcess's default 5 s escalation
        # can SIGKILL the straggler mid-teardown otherwise).
        "HOROVOD_PREEMPT_GRACE_SECS": "20",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--multihost",
         "-H", "127.0.0.1:1,127.0.0.2:1", "--min-np", "1",
         "--max-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=scaled_timeout(600),
        env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Every batch finished; the straggler's respawn recovered too.
    assert "DONE rank=0" in proc.stdout, proc.stdout
    # Detection fired and actuated as a drain (driver journal).
    kinds = {}
    detection = None
    for rec in metrics.iter_events(str(events_dir), merged=True):
        kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
        if rec["kind"] == "straggler_detected" and detection is None:
            detection = rec
    assert detection is not None, kinds
    assert detection["action"] == "drain"
    assert float(detection["score"]) >= 2.0
    assert kinds.get("straggler_drain_order"), kinds
    assert kinds.get("drained"), kinds
    # Planned removal, not a failure: no blacklist anywhere.
    assert "blacklisting host" not in proc.stderr, proc.stderr
    assert not kinds.get("blacklist"), kinds
