"""Spark estimator-layer tests.

Reference parity: ``test/integration/test_spark.py`` /
``test_spark_keras.py`` / ``test_spark_torch.py`` + the Store tests —
run WITHOUT a Spark cluster, exactly as the reference runs local-mode
Spark: the ``LocalBackend`` launches a real multi-process world through
the launcher, and the Store/params/dataset pieces are exercised on the
local filesystem.
"""

import os
import sys

import numpy as np
import pandas as pd
import pytest
import torch

from horovod_tpu.spark.common import (EstimatorParams, LocalBackend,
                                      LocalStore, Store)
from horovod_tpu.spark.common.util import (check_validation,
                                           materialize_dataframe,
                                           read_parquet_shard)


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path / "x"))
    assert isinstance(s, LocalStore)
    assert Store.create("dbfs:/tmp/x").prefix_path.startswith("/dbfs")


def test_store_layout_and_io(tmp_path):
    s = LocalStore(str(tmp_path))
    assert "intermediate_train_data" in s.get_train_data_path()
    assert s.get_checkpoint_path("r1").endswith("checkpoint.bin")
    p = os.path.join(s.get_run_path("r1"), "blob.bin")
    s.write(p, b"abc")
    assert s.exists(p) and s.read(p) == b"abc"
    s.delete(s.get_run_path("r1"))
    assert not s.exists(p)


def test_store_sync_fn(tmp_path):
    s = LocalStore(str(tmp_path / "store"))
    local = tmp_path / "local"
    (local / "sub").mkdir(parents=True)
    (local / "a.txt").write_text("A")
    (local / "sub" / "b.txt").write_text("B")
    s.sync_fn("run7")(str(local))
    run = s.get_run_path("run7")
    assert open(os.path.join(run, "a.txt")).read() == "A"
    assert open(os.path.join(run, "sub", "b.txt")).read() == "B"


def test_estimator_params_accessors():
    p = EstimatorParams(epochs=3)
    assert p.getEpochs() == 3
    p.setBatchSize(64).setVerbose(0)
    assert p.batch_size == 64 and p.getVerbose() == 0
    with pytest.raises(ValueError):
        EstimatorParams(bogus=1)
    with pytest.raises(ValueError):
        EstimatorParams()._check_params()  # model/store missing


def test_check_validation():
    assert check_validation(None) == 0.0
    assert check_validation(0.25) == 0.25
    with pytest.raises(ValueError):
        check_validation(1.5)


def _df(n=32):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w = np.arange(1, 5, dtype=np.float32)
    y = x @ w
    return pd.DataFrame({"features": [list(r) for r in x],
                         "label": y})


def test_materialize_and_shard(tmp_path):
    store = LocalStore(str(tmp_path))
    df = _df(10)
    materialize_dataframe(df, store.get_train_data_path(), store)
    x0, y0 = read_parquet_shard(store.get_train_data_path(), 0, 2,
                                ["features"], ["label"])
    x1, y1 = read_parquet_shard(store.get_train_data_path(), 1, 2,
                                ["features"], ["label"])
    assert x0.shape == (5, 4) and x1.shape == (5, 4)
    assert len(set(map(float, y0)) & set(map(float, y1))) == 0


@pytest.mark.skipif(sys.platform != "linux", reason="launcher is posix")
def test_torch_estimator_end_to_end(tmp_path):
    import torch
    from horovod_tpu.spark.torch import TorchEstimator
    store = LocalStore(str(tmp_path))
    model = torch.nn.Linear(4, 1)
    est = TorchEstimator(model=model, store=store, epochs=2,
                         batch_size=8, verbose=0,
                         backend=LocalBackend(num_proc=2))
    fitted = est.fit(_df(32))
    assert len(fitted.history) == 2
    assert fitted.history[1]["loss"] <= fitted.history[0]["loss"] * 2
    out = fitted.transform(_df(8))
    assert "label__output" in out.columns
    # final model persisted into the store
    assert store.exists(store.get_checkpoint_path(fitted.run_id))


@pytest.mark.skipif(sys.platform != "linux", reason="launcher is posix")
def test_keras_estimator_end_to_end(tmp_path):
    import keras
    from horovod_tpu.spark.keras import KerasEstimator
    store = LocalStore(str(tmp_path))
    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(1, use_bias=False)])
    est = KerasEstimator(model=model, store=store, optimizer="sgd",
                         loss="mse", epochs=1, batch_size=8, verbose=0,
                         backend=LocalBackend(num_proc=1))
    fitted = est.fit(_df(16))
    assert "loss" in fitted.history
    pred = fitted.predict(np.zeros((2, 4), np.float32))
    assert pred.shape[0] == 2


class DuckModule(torch.nn.Module):
    """LightningModule training contract without lightning installed
    (the estimator is duck-typed).  Top-level: ``torch.save`` pickles
    the class by reference, so workers must import it by name."""

    def __init__(self):
        super().__init__()
        self.lin = torch.nn.Linear(4, 1)

    def forward(self, x):
        return self.lin(x)

    def training_step(self, batch, batch_idx):
        x, y = batch
        out = self(x).squeeze(-1)
        return {"loss": torch.nn.functional.mse_loss(out, y.squeeze(-1))}

    def configure_optimizers(self):
        return ([torch.optim.SGD(self.parameters(), lr=0.05)], [])


def test_lightning_estimator_end_to_end(tmp_path):
    from horovod_tpu.spark.lightning import TorchEstimator
    store = LocalStore(str(tmp_path))
    est = TorchEstimator(model=DuckModule(), store=store,
                         epochs=2, batch_size=8, verbose=0,
                         backend=LocalBackend(num_proc=2))
    fitted = est.fit(_df(32))
    assert len(fitted.history) == 2
    assert fitted.history[1]["loss"] <= fitted.history[0]["loss"] * 2
    pred = fitted.predict(np.zeros((3, 4), np.float32))
    assert pred.shape[0] == 3
    assert store.exists(store.get_checkpoint_path(fitted.run_id))


def test_lightning_estimator_rejects_plain_module(tmp_path):
    import torch
    from horovod_tpu.spark.lightning import TorchEstimator
    store = LocalStore(str(tmp_path))
    est = TorchEstimator(model=torch.nn.Linear(4, 1), store=store,
                         epochs=1, batch_size=8, verbose=0,
                         backend=LocalBackend(num_proc=1))
    with pytest.raises(Exception, match="training_step"):
        est.fit(_df(8))


def _mapper_fn():
    # Runs inside the mapper body: a real size-1 tcp world bootstrapped
    # through the rendezvous env the mapper installs.
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    try:
        out = hvd.allreduce(np.array([2.0, 3.0], np.float32),
                            op=hvd.Sum, name="spark_mapper_ar")
        return {"rank": hvd.rank(), "sum": [float(v) for v in out]}
    finally:
        hvd.shutdown()


def test_spark_run_mapper_body_executes(monkeypatch):
    """Execute _make_mapper's barrier-task body in-process under a fake
    BarrierTaskContext: env wiring, the real rendezvous KV, fn
    execution in a real tcp world, the barrier call, and the (rank,
    result) yield are all covered without a Spark cluster."""
    import types

    from horovod_tpu.runner import util as runner_util
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.spark import _make_mapper

    barrier_calls = []

    class FakeTaskInfo:
        def __init__(self, address):
            self.address = address

    class FakeBarrierTaskContext:
        @staticmethod
        def get():
            return FakeBarrierTaskContext()

        def partitionId(self):
            return 0

        def getTaskInfos(self):
            return [FakeTaskInfo("127.0.0.1:41000")]

        def barrier(self):
            barrier_calls.append(True)

    fake_pyspark = types.ModuleType("pyspark")
    fake_pyspark.BarrierTaskContext = FakeBarrierTaskContext
    monkeypatch.setitem(sys.modules, "pyspark", fake_pyspark)

    secret = runner_util.make_secret()
    server = RendezvousServer(secret=secret)
    port = server.start()
    saved_env = dict(os.environ)
    try:
        mapper = _make_mapper(_mapper_fn, (), {}, 1,
                              "127.0.0.1:%d" % port, secret,
                              {"HOROVOD_EXTRA_MARK": "1"})
        results = list(mapper(iter([0])))
        assert results == [(0, {"rank": 0, "sum": [2.0, 3.0]})]
        assert barrier_calls == [True]
        # The mapper installed the world env (executor-side semantics).
        assert os.environ["HOROVOD_RANK"] == "0"
        assert os.environ["HOROVOD_EXTRA_MARK"] == "1"
    finally:
        server.stop()
        os.environ.clear()
        os.environ.update(saved_env)


def test_lightning_first_optimizer_contracts():
    # Every documented configure_optimizers return shape resolves to
    # the first optimizer; a dict without one fails loudly.
    from horovod_tpu.spark.lightning import _first_optimizer
    opt = torch.optim.SGD([torch.nn.Parameter(torch.zeros(2))], lr=0.1)
    assert _first_optimizer(opt) is opt
    assert _first_optimizer([opt]) is opt
    assert _first_optimizer(([opt], [])) is opt
    assert _first_optimizer({"optimizer": opt, "lr_scheduler": None}) \
        is opt
    assert _first_optimizer([{"optimizer": opt}]) is opt
    with pytest.raises(ValueError, match="optimizer"):
        _first_optimizer({"lr_scheduler": None})
    with pytest.raises(ValueError, match="no optimizer"):
        _first_optimizer([])


def test_arrow_fs_store_executes_hdfs_logic(tmp_path):
    # The exact code HDFSStore runs, executed against a local
    # pyarrow filesystem (the reference tests its HDFS store the same
    # way: a local fs standing in for the cluster).
    pafs = pytest.importorskip("pyarrow.fs")
    from horovod_tpu.spark.common import ArrowFsStore
    s = ArrowFsStore(str(tmp_path / "store"), pafs.LocalFileSystem())
    p = os.path.join(s.get_run_path("r1"), "sub", "blob.bin")
    assert not s.exists(p)
    s.write(p, b"abc")
    assert s.exists(p) and s.read(p) == b"abc"
    assert any(e.endswith("blob.bin")
               for e in s.listdir(os.path.dirname(p)))
    # sync_fn mirrors a local tree into the run path
    local = tmp_path / "local"
    (local / "d").mkdir(parents=True)
    (local / "a.txt").write_text("A")
    (local / "d" / "b.txt").write_text("B")
    s.sync_fn("r2")(str(local))
    assert s.read(os.path.join(s.get_run_path("r2"), "a.txt")) == b"A"
    assert s.read(os.path.join(s.get_run_path("r2"), "d",
                               "b.txt")) == b"B"
    s.delete(s.get_run_path("r1"))
    assert not s.exists(p)
    s.delete(p)  # deleting a missing path is a no-op
