"""In-program (shard_map) collective matrix for horovod_tpu.jax.spmd —
the primitives hand-written SPMD steps build on (reference parity: the
collective matrix of test/parallel/*, here for the jit plane)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.jax import spmd

SIZE = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < SIZE:
        pytest.skip("needs %d devices" % SIZE)
    return Mesh(np.asarray(devs[:SIZE]), (spmd.DEFAULT_AXIS,))


def _run(mesh, fn, x, out_specs=P(spmd.DEFAULT_AXIS)):
    mapped = jax.shard_map(fn, mesh=mesh,
                           in_specs=P(spmd.DEFAULT_AXIS),
                           out_specs=out_specs, check_vma=False)
    return np.asarray(jax.jit(mapped)(x))


def test_allreduce_ops(mesh):
    x = np.arange(SIZE * 3, dtype=np.float32).reshape(SIZE, 3) + 1.0
    for op, ref in [(spmd.SUM, x.sum(0)), (spmd.AVERAGE, x.mean(0)),
                    (spmd.MIN, x.min(0)), (spmd.MAX, x.max(0)),
                    (spmd.PRODUCT, x.prod(0))]:
        out = _run(mesh, lambda v, op=op: spmd.allreduce(v[0], op)[None],
                   jnp.asarray(x))
        np.testing.assert_allclose(out[0], ref, rtol=1e-4,
                                   err_msg=str(op))


def test_allreduce_scales(mesh):
    x = np.ones((SIZE, 4), np.float32)
    out = _run(mesh, lambda v: spmd.allreduce(
        v[0], spmd.SUM, prescale_factor=0.5, postscale_factor=3.0)[None],
        jnp.asarray(x))
    np.testing.assert_allclose(out[0], SIZE * 0.5 * 3.0)


def test_rank_size_allgather_broadcast(mesh):
    x = np.tile(np.arange(SIZE, dtype=np.float32)[:, None], (1, 2))

    def fn(v):
        r = spmd.rank()
        n = spmd.size()
        g = spmd.allgather(v)          # [SIZE, 2]
        b = spmd.broadcast(v, root_rank=3)
        return (g + 0.0 * r + 0.0 * n)[None], b[None]

    mapped = jax.shard_map(fn, mesh=mesh, in_specs=P(spmd.DEFAULT_AXIS),
                           out_specs=(P(spmd.DEFAULT_AXIS),
                                      P(spmd.DEFAULT_AXIS)),
                           check_vma=False)
    g, b = jax.jit(mapped)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g)[0], x)
    np.testing.assert_allclose(np.asarray(b)[:, 0, :],
                               np.tile(x[3], (SIZE, 1)))


def test_alltoall_and_reducescatter(mesh):
    x = np.arange(SIZE * SIZE, dtype=np.float32).reshape(SIZE, SIZE)

    def fn(v):
        a2a = spmd.alltoall(v[0][:, None])       # [SIZE, 1]
        rs = spmd.reducescatter(v[0][:, None], op=spmd.SUM)
        return a2a[None], rs[None]

    mapped = jax.shard_map(fn, mesh=mesh, in_specs=P(spmd.DEFAULT_AXIS),
                           out_specs=(P(spmd.DEFAULT_AXIS),
                                      P(spmd.DEFAULT_AXIS)),
                           check_vma=False)
    a2a, rs = jax.jit(mapped)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(a2a)[..., 0], x.T)
    # reducescatter row r = sum over ranks of their r-th element.
    np.testing.assert_allclose(np.asarray(rs)[:, 0, 0], x.sum(0))


def test_ppermute_ring(mesh):
    x = np.arange(SIZE, dtype=np.float32)[:, None]
    perm = [(i, (i + 1) % SIZE) for i in range(SIZE)]
    out = _run(mesh, lambda v: spmd.ppermute(v, perm), jnp.asarray(x))
    np.testing.assert_allclose(out[:, 0], np.roll(x[:, 0], 1))
