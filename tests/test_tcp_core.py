"""Multi-process native-core tests: a real N-process world on localhost
(reference test strategy: Gloo-on-localhost IS the test backend,
SURVEY.md §4)."""

import os

import pytest

from tests.utils.spawn import assert_world_ok, spawn_world

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "utils",
                      "tcp_worker.py")


def _spawn_world(size, scenario, extra_env=None, timeout=120):
    env = {"TEST_SCENARIO": scenario}
    env.update(extra_env or {})
    return spawn_world(WORKER, size, extra_env=env, timeout=timeout)


def _assert_ok(outs):
    assert_world_ok(outs)


@pytest.mark.parametrize("size", [2, 4])
def test_tcp_collective_matrix(size):
    _assert_ok(_spawn_world(size, "collectives"))


def test_tcp_response_cache_fast_path():
    _assert_ok(_spawn_world(2, "cache"))


def test_tcp_cache_eviction_under_capacity_pressure():
    # LRU eviction with id reuse must stay rank-identical (evictions
    # follow broadcast order); 10 rotating tensors against capacity 4.
    _assert_ok(_spawn_world(2, "cache_evict",
                            extra_env={"HOROVOD_CACHE_CAPACITY": "4"}))


@pytest.mark.parametrize("size", [2, 4])
def test_tcp_group_name_reuse_changed_membership(size):
    # Regression: reusing a grouped_allreduce name with different member
    # count/shapes deadlocked — cached members bypassed the group
    # barrier while the shape-changed member waited in pending forever.
    # Size 4 adds process-set-scoped grouped negotiation.
    _assert_ok(_spawn_world(size, "regroup"))


def test_tcp_join_uneven_data():
    _assert_ok(_spawn_world(3, "join"))


def test_tcp_error_propagation():
    _assert_ok(_spawn_world(2, "error"))


def test_tcp_collective_deadline_distinct_abort():
    # ISSUE 18 C++ mirror: HOROVOD_COLLECTIVE_TIMEOUT_SECS bounds a
    # negotiation-phase hang in the native core too (python-less
    # worlds).  A tensor only rank 0 submits must error-complete after
    # the deadline with "collective deadline exceeded" — a message
    # DISTINCT from the stall inspector's drain-shaped abort, because
    # elastic routes the two differently (restore vs drain).
    outs = _spawn_world(2, "deadline", extra_env={
        "HOROVOD_COLLECTIVE_TIMEOUT_SECS": "2",
    })
    assert_world_ok(outs, marker="DEADLINE_OK")


def test_tcp_timeline_written(tmp_path):
    tl = str(tmp_path / "tl.json")
    _assert_ok(_spawn_world(2, "cache", extra_env={"HOROVOD_TIMELINE": tl}))
    import json
    events = json.load(open(tl + ".0"))
    assert any(e.get("name", "").startswith("NEGOTIATE") for e in events)
    assert any(e.get("name") == "ALLREDUCE" for e in events)


def test_core_library_builds():
    from horovod_tpu.core.client import core_library_available
    assert core_library_available()


def test_world_reinit():
    """Shutdown → init must yield a working fresh world (the elastic
    path); regression: controller shutdown/join rank-sets leaking across
    worlds killed the new background loop after one cycle."""
    import time
    import horovod_tpu.torch as hvd
    import torch
    for w in range(2):
        hvd.init()
        time.sleep(0.2)  # let a few negotiation cycles run
        out = hvd.broadcast(torch.ones(2), 0, name="reinit_b%d" % w)
        assert torch.equal(out, torch.ones(2))
        hvd.shutdown()


def test_tcp_hierarchical_allreduce():
    # fake a 2-host x 2-slot topology on localhost: intra-host ring,
    # leader ring across "hosts", intra-host broadcast — results must
    # match the flat ring exactly
    _assert_ok(_spawn_world(4, "collectives", extra_env={
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HVD_TPU_HOST_OF_RANK": "0,0,1,1",
    }))


def test_tcp_hierarchical_uneven_groups():
    # 3 ranks on host0, 1 on host1 (uneven groups + singleton leader)
    _assert_ok(_spawn_world(4, "collectives", extra_env={
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HVD_TPU_HOST_OF_RANK": "0,0,0,1",
    }))


def test_tcp_autotune_samples_written(tmp_path):
    # rank 0 runs the BO autotuner in the C++ core: with pacing lowered
    # it must SCORE samples (data rows), not just write the csv header.
    # The r14 crash-safe writer rank-stamps the path (".r<rank>", one
    # writer per file, O_APPEND) so concurrent worlds never interleave.
    log = str(tmp_path / "autotune.csv")
    _assert_ok(_spawn_world(2, "autotune", extra_env={
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_LOG": log,
        "HVD_TPU_AUTOTUNE_WARMUP_CYCLES": "1",
        "HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE": "2",
    }))
    assert not os.path.exists(log)  # no writer at the raw path anymore
    lines = open(log + ".r0").read().strip().splitlines()
    assert lines[0].startswith("sample,")
    assert len(lines) >= 3, lines  # header + >=2 scored samples
    # A rerun sharing the log path appends instead of clobbering, and
    # the header is not restamped.
    _assert_ok(_spawn_world(2, "autotune", extra_env={
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_LOG": log,
        "HVD_TPU_AUTOTUNE_WARMUP_CYCLES": "1",
        "HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE": "2",
    }))
    lines2 = open(log + ".r0").read().strip().splitlines()
    assert len(lines2) > len(lines), (lines, lines2)
    assert sum(1 for ln in lines2 if ln.startswith("sample,")) == 1


def test_tcp_hierarchical_interleaved_hosts():
    # ranks alternate hosts (0,1,0,1): group blocks are NON-contiguous
    # in member order, so this catches any ordering mistake in the
    # hierarchical allgather/allreduce paths
    _assert_ok(_spawn_world(4, "collectives", extra_env={
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HVD_TPU_HOST_OF_RANK": "0,1,0,1",
    }))


def test_tcp_hierarchical_big_allgather():
    # G=2 leader exchange with multi-MB payloads: completes only with
    # the ordered send/recv protocol (simultaneous blocking sends
    # would deadlock once socket buffers fill)
    _assert_ok(_spawn_world(4, "big_allgather", extra_env={
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HVD_TPU_HOST_OF_RANK": "0,0,1,1",
    }, timeout=180))


def test_tcp_hierarchical_allgather_own_knob():
    # HOROVOD_HIERARCHICAL_ALLGATHER selects the allgather algorithm
    # independently of the allreduce knob (reference exposes both).
    _assert_ok(_spawn_world(4, "big_allgather", extra_env={
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "0",
        "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
        "HVD_TPU_HOST_OF_RANK": "0,0,1,1",
    }, timeout=180))


EXTERNAL_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "utils", "external_worker.py")


def _spawn_external_world(size, scenario, timeout=120):
    return spawn_world(EXTERNAL_WORKER, size,
                       extra_env={"TEST_SCENARIO": scenario},
                       timeout=timeout)


@pytest.mark.parametrize("size", [2, 3])
def test_external_payload_negotiation_order(size):
    # Device-payload ops: negotiation must deliver one identical
    # execution order on every rank (verified cross-rank by the worker).
    _assert_ok(_spawn_external_world(size, "order"))


def test_external_payload_mixed_with_host_ops():
    # External and host ops interleave; external never fuses with host,
    # executor failures surface through the handle.
    _assert_ok(_spawn_external_world(2, "mixed"))


# -- sanitizer leg ----------------------------------------------------------

CORE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(WORKER)))), "horovod_tpu", "core")


def _sanitized_env(kind, runtime_so):
    """Spawn env for a sanitized world: the instrumented core is
    dlopen'd into an UNinstrumented python, so the sanitizer runtime
    must be preloaded, and python must use raw malloc — pymalloc's
    arena-internal reuse is invisible to the runtime and leaves stale
    sync metadata on reused addresses (phantom reports)."""
    import subprocess
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=%s" % runtime_so],
            capture_output=True, check=True, timeout=60,
            text=True).stdout.strip()
    except Exception:
        return None
    if not os.path.isabs(out):  # "libtsan.so" echoed back: not found
        return None
    return {"LD_PRELOAD": out, "PYTHONMALLOC": "malloc"}


def _sanitized_lib(kind):
    """Build the side-by-side instrumented core (make SANITIZE=<kind>),
    or None when the toolchain can't produce it (missing libtsan etc.)
    — the caller skips rather than fails."""
    import subprocess
    try:
        subprocess.run(["make", "-s", "-j", "SANITIZE=%s" % kind],
                       cwd=CORE_DIR, check=True, capture_output=True,
                       timeout=600)
    except Exception:
        return None
    lib = os.path.join(CORE_DIR, "libhvdtpu_core_%s.so" % kind)
    return lib if os.path.exists(lib) else None


@pytest.mark.slow
def test_tcp_collectives_under_tsan():
    """Full 2-proc collective matrix against a ThreadSanitizer build:
    the enqueue / background-negotiation / completion threads must be
    race-free under real interleavings, not just under the lock graph
    graftlint certifies statically.  halt_on_error turns any report
    into a nonzero worker exit the harness rejects."""
    lib = _sanitized_lib("thread")
    env = _sanitized_env("thread", "libtsan.so")
    if lib is None or env is None:
        pytest.skip("TSan core build unavailable")
    supp = os.path.join(os.path.dirname(os.path.abspath(WORKER)),
                        "tsan.supp")
    env.update({
        "HVD_TPU_CORE_LIB": lib,
        "TSAN_OPTIONS":
            "halt_on_error=1 exitcode=66 suppressions=%s" % supp,
    })
    _assert_ok(_spawn_world(2, "collectives", extra_env=env,
                            timeout=300))


@pytest.mark.slow
def test_tcp_collectives_under_asan():
    """Same matrix under AddressSanitizer: wire (de)serialization and
    the fusion-buffer copies stay in bounds."""
    lib = _sanitized_lib("address")
    env = _sanitized_env("address", "libasan.so")
    if lib is None or env is None:
        pytest.skip("ASan core build unavailable")
    env.update({
        "HVD_TPU_CORE_LIB": lib,
        # leak detection off: the long-lived CoreState singleton and
        # python interpreter allocations are intentional.
        "ASAN_OPTIONS": "halt_on_error=1:exitcode=66:detect_leaks=0",
    })
    _assert_ok(_spawn_world(2, "collectives", extra_env=env,
                            timeout=300))
