"""TensorFlow adapter tests.

Reference parity: ``test/parallel/test_tensorflow.py`` /
``test_tensorflow2_keras.py`` — collectives on tf tensors, gradient
registration, DistributedGradientTape, the Keras DistributedOptimizer,
variable broadcast, compression, local aggregation.  Single-process
cases run a size-1 tcp world (the multi-process wire behavior is
covered by the launcher/core tests).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")


@pytest.fixture(scope="module")
def hvd():
    import horovod_tpu.tensorflow as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


def test_size1_collectives(hvd):
    assert hvd.size() == 1 and hvd.rank() == 0
    t = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    out = hvd.allreduce(t, op=hvd.Sum, name="tf_ar")
    assert np.allclose(out.numpy(), t.numpy())
    g = hvd.allgather(t, name="tf_ag")
    assert np.allclose(g.numpy(), t.numpy())
    b = hvd.broadcast(t, root_rank=0, name="tf_bc")
    assert np.allclose(b.numpy(), t.numpy())
    rs = hvd.reducescatter(t, name="tf_rs")
    assert np.allclose(rs.numpy(), t.numpy())
    a2a = hvd.alltoall(tf.range(4), name="tf_a2a")
    assert np.allclose(a2a.numpy(), np.arange(4))
    outs = hvd.grouped_allreduce([t, 2 * t], op=hvd.Sum, name="tf_gar")
    assert np.allclose(outs[1].numpy(), 2 * t.numpy())
    gg = hvd.grouped_allgather([t, 3 * t], name="tf_gag")
    assert np.allclose(gg[1].numpy(), 3 * t.numpy())
    gr = hvd.grouped_reducescatter([t, 2 * t], op=hvd.Sum, name="tf_grs")
    assert np.allclose(gr[0].numpy(), t.numpy())

    @tf.function
    def grouped_fn(x):
        return hvd.grouped_allgather([x, x + 1.0], name="tf_gag_fn")

    a, b = grouped_fn(tf.ones((2, 2)))
    assert np.allclose(b.numpy(), 2.0)
    hvd.barrier()


def test_world_info_ops(hvd):
    # Graph-mode world-info tensors (reference size_op/rank_op/...):
    # values are read at EXECUTION time inside tf.function, so elastic
    # re-inits show through without retracing.
    assert int(hvd.size_op()) == 1
    assert int(hvd.rank_op()) == 0
    assert int(hvd.local_size_op()) == 1
    assert int(hvd.local_rank_op()) == 0
    assert int(hvd.process_set_included_op()) == 1

    @tf.function
    def scaled(x):
        return x * tf.cast(hvd.size_op(), tf.float32) \
            + tf.cast(hvd.rank_op(), tf.float32)

    out = scaled(tf.constant([2.0]))
    assert np.allclose(out.numpy(), [2.0])
    ps = hvd.ProcessSet([0])
    hvd.add_process_set(ps)
    try:
        assert int(hvd.size_op(ps.process_set_id)) == 1
        assert int(hvd.process_set_included_op(ps.process_set_id)) == 1
    finally:
        hvd.remove_process_set(ps)


def test_bfloat16_wire(hvd):
    t = tf.cast(tf.reshape(tf.range(8, dtype=tf.float32), (2, 4)),
                tf.bfloat16)
    out = hvd.allreduce(t, op=hvd.Sum, name="tf_bf16")
    assert out.dtype == tf.bfloat16
    assert np.allclose(tf.cast(out, tf.float32).numpy(),
                       np.arange(8, dtype=np.float32).reshape(2, 4))


def test_allreduce_gradient_registered(hvd):
    x = tf.Variable([1.0, 2.0, 3.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.allreduce(x, op=hvd.Sum, name="tf_grad"))
    g = tape.gradient(y, x)
    # size-1 world: d(allreduce(x))/dx = allreduce(ones) = ones
    assert np.allclose(g.numpy(), np.ones(3))


def test_distributed_gradient_tape(hvd):
    v = tf.Variable([2.0, 4.0])
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * v)
    grads = tape.gradient(loss, [v])
    assert np.allclose(grads[0].numpy(), [4.0, 8.0])


def test_local_gradient_aggregation(hvd):
    from horovod_tpu.tensorflow.gradient_aggregation import (
        LocalGradientAggregationHelper)
    calls = []

    def fake_allreduce(grads):
        calls.append(len(grads))
        return grads

    agg = LocalGradientAggregationHelper(2, fake_allreduce)
    should, _ = agg.apply([tf.constant([2.0])])
    assert not should and not calls
    should, grads = agg.apply([tf.constant([4.0])])
    # Boundary: (2+4)/2 = 3, one allreduce fired.
    assert should and calls == [1]
    assert np.allclose(grads[0].numpy(), [3.0])


def test_grouped_gradient_paths(hvd):
    # num_groups through the tape inside tf.function (symbolic grouped
    # staging) matches ungrouped values.
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0], [4.0]])
    v3 = tf.Variable(5.0)

    @tf.function
    def step():
        with hvd.DistributedGradientTape(tf.GradientTape(),
                                         num_groups=2) as tape:
            loss = tf.reduce_sum(v1) * v3 + tf.reduce_sum(v2)
        return tape.gradient(loss, [v1, v2, v3])

    g1, g2, g3 = step()
    assert np.allclose(g1.numpy(), [5.0, 5.0])
    assert np.allclose(g2.numpy(), [[1.0], [1.0]])
    assert np.allclose(g3.numpy(), 3.0)

    # Keras optimizer with num_groups still trains.
    from tensorflow import keras
    model = keras.Sequential([keras.layers.Dense(2, input_shape=(3,))])
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1), num_groups=2)
    x = tf.ones((4, 3))
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean(model(x) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply(grads, model.trainable_variables)

    # Keras 3 apply(grads) without variables: explicit groups match
    # against the optimizer's own built variable list.
    opt2 = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1),
        groups=[model.trainable_variables])
    opt2.build(model.trainable_variables)
    opt2.apply(grads)

    # Explicit variable groups + local aggregation cannot be matched.
    with pytest.raises(ValueError, match="num_groups"):
        hvd.DistributedOptimizer(
            keras.optimizers.SGD(), groups=[model.trainable_variables],
            backward_passes_per_step=2)


def test_compression_fp16(hvd):
    t = tf.constant([1.5, 2.5], dtype=tf.float32)
    c, ctx = hvd.Compression.fp16.compress(t)
    assert c.dtype == tf.float16
    d = hvd.Compression.fp16.decompress(c, ctx)
    assert d.dtype == tf.float32 and np.allclose(d.numpy(), [1.5, 2.5])


def test_broadcast_variables(hvd):
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable(np.eye(2, dtype=np.float32))
    hvd.broadcast_variables([v1, v2], root_rank=0)
    assert np.allclose(v1.numpy(), [1.0, 2.0])
    assert np.allclose(v2.numpy(), np.eye(2))


def test_broadcast_and_allgather_object(hvd):
    obj = {"epoch": 3, "arr": np.arange(4)}
    out = hvd.broadcast_object(obj, root_rank=0, name="tf_obj")
    assert out["epoch"] == 3 and np.allclose(out["arr"], np.arange(4))
    gathered = hvd.allgather_object("x", name="tf_objs")
    assert gathered == ["x"]


def test_keras_distributed_optimizer(hvd):
    import keras
    model = keras.Sequential(
        [keras.layers.Dense(2, input_shape=(4,), use_bias=False)])
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
    assert type(opt).__name__ == "DistributedSGD"
    model.compile(optimizer=opt, loss="mse")
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    y = np.zeros((8, 2), np.float32)
    w0 = model.get_weights()[0].copy()
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)
    assert not np.allclose(model.get_weights()[0], w0)


def test_keras_optimizer_matches_plain(hvd):
    import keras
    rs = np.random.RandomState(1)
    x = rs.randn(16, 3).astype(np.float32)
    y = rs.randn(16, 1).astype(np.float32)

    def build():
        keras.utils.set_random_seed(7)
        m = keras.Sequential([keras.layers.Dense(1, input_shape=(3,))])
        return m

    m_plain, m_dist = build(), build()
    m_dist.set_weights(m_plain.get_weights())
    m_plain.compile(optimizer=keras.optimizers.SGD(0.05), loss="mse")
    m_dist.compile(
        optimizer=hvd.DistributedOptimizer(keras.optimizers.SGD(0.05)),
        loss="mse")
    m_plain.fit(x, y, epochs=2, batch_size=16, shuffle=False, verbose=0)
    m_dist.fit(x, y, epochs=2, batch_size=16, shuffle=False, verbose=0)
    for a, b in zip(m_plain.get_weights(), m_dist.get_weights()):
        assert np.allclose(a, b, atol=1e-6)


def test_elastic_state(hvd):
    import keras
    model = keras.Sequential(
        [keras.layers.Dense(1, input_shape=(2,), use_bias=False)])
    model.build((None, 2))
    state = hvd.elastic.TensorFlowKerasState(model, epoch=0)
    w0 = model.get_weights()[0].copy()
    state.commit()
    model.weights[0].assign(np.zeros_like(w0))
    state.epoch = 5
    state.restore()
    assert state.epoch == 0
    assert np.allclose(model.get_weights()[0], w0)

    # Plain-variable state (reference TensorFlowState).
    v = tf.Variable([1.0, 2.0])
    vs = hvd.elastic.TensorFlowState(variables=[v], step=3)
    vs.commit()
    v.assign([9.0, 9.0])
    vs.step = 7
    vs.restore()
    assert vs.step == 3
    assert np.allclose(v.numpy(), [1.0, 2.0])
    vs.sync()  # size-1: broadcast no-op, values keep
    assert np.allclose(v.numpy(), [1.0, 2.0])


def test_allgather_gradient_registered(hvd):
    x = tf.Variable([[1.0, 2.0], [3.0, 4.0]])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.allgather(x, name="tf_ag_grad") * 2.0)
    g = tape.gradient(y, x)
    assert g is not None
    # size-1: gathered == x, so grad is 2 everywhere.
    assert np.allclose(g.numpy(), np.full((2, 2), 2.0))


def test_broadcast_gradient_registered(hvd):
    x = tf.Variable([1.0, 5.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.broadcast(x, root_rank=0,
                                        name="tf_bc_grad") * 3.0)
    g = tape.gradient(y, x)
    # rank 0 IS the root in a size-1 world: grad = sum over ranks = 3.
    assert np.allclose(g.numpy(), [3.0, 3.0])


def test_reducescatter_gradient_registered(hvd):
    x = tf.Variable([[1.0], [2.0]])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.reducescatter(x, name="tf_rs_grad"))
    g = tape.gradient(y, x)
    assert g is not None and np.allclose(g.numpy(), np.ones((2, 1)))


def test_alltoall_gradient_registered(hvd):
    x = tf.Variable([[1.0, 2.0]])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.alltoall(x, name="tf_a2a_grad") * 4.0)
    g = tape.gradient(y, x)
    assert g is not None and np.allclose(g.numpy(), [[4.0, 4.0]])


def test_collectives_inside_tf_function(hvd):
    @tf.function
    def step(x):
        a = hvd.allreduce(x, op=hvd.Sum, name="tfn_ar")
        b = hvd.alltoall(x, name="tfn_a2a")
        c = hvd.grouped_allreduce([x, x * 2], op=hvd.Sum,
                                  name="tfn_gar")
        return a + b + c[0] + c[1]

    x = tf.constant([[1.0, 2.0]])
    out = step(x)
    # size-1 world: every collective is identity → 1+1+1+2 = 5x.
    assert np.allclose(out.numpy(), [[5.0, 10.0]])


def test_multirank_native_op_jit_compile():
    # HOROVOD_ENABLE_XLA_OPS=1: allreduce inside
    # tf.function(jit_compile=True) via the native op's XLA custom-call
    # (reference xla_mpi_ops.cc), at world size 2 over the real core.
    import os
    from tests.utils.spawn import spawn_world, assert_world_ok
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "utils", "tf_adapter_worker.py")
    assert_world_ok(
        spawn_world(worker, 2,
                    extra_env={"HOROVOD_ENABLE_XLA_OPS": "1"}),
        "TF_ADAPTER_OK")


def test_alltoall_explicit_splits_inside_tf_function(hvd):
    # Closes the r2 documented edge: explicit splits now work in graph
    # mode — the staged op returns (output, recv_splits) as TENSORS
    # (reference graph contract) and the backward reverse-routes with
    # the recorded receive splits.
    @tf.function
    def step(x):
        out, recv = hvd.alltoall(x, splits=[4], name="tf_a2a_fn")
        return out * 2.0, recv

    x = tf.range(4, dtype=tf.float32)
    out, recv = step(x)
    assert np.allclose(out.numpy(), np.arange(4) * 2.0)
    assert recv.numpy().tolist() == [4]

    @tf.function
    def grad_step(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            out, _ = hvd.alltoall(x, splits=[4], name="tf_a2a_fn_g")
            y = tf.reduce_sum(out * 3.0)
        return tape.gradient(y, x)

    g = grad_step(x)
    assert np.allclose(g.numpy(), np.full(4, 3.0))


def test_sync_batch_norm_symbolic_training_flag(hvd):
    # Keras passes `training` as a symbolic tensor inside tf.function
    # (smart_cond contract); the layer must trace a tf.cond over both
    # branches instead of evaluating the tensor as a Python bool.
    import tensorflow as tf

    bn = hvd.SyncBatchNormalization(epsilon=1e-5)
    x = tf.random.normal([8, 4])
    bn(x, training=True)  # build + one eager train step

    @tf.function
    def step(inp, training):
        return bn(inp, training=training)

    train_out = step(x, tf.constant(True))
    mean = tf.reduce_mean(x, axis=0)
    var = tf.math.reduce_variance(x, axis=0)
    want = (x - mean) * tf.math.rsqrt(var + 1e-5) * bn.gamma + bn.beta
    assert np.allclose(train_out.numpy(), want.numpy(), atol=1e-4)

    infer_out = step(x, tf.constant(False))
    want_inf = ((x - bn.moving_mean)
                * tf.math.rsqrt(bn.moving_variance + 1e-5)
                * bn.gamma + bn.beta)
    assert np.allclose(infer_out.numpy(), want_inf.numpy(), atol=1e-4)
    # The train branch updated the moving averages under the cond.
    assert not np.allclose(bn.moving_mean.numpy(), np.zeros(4))


def test_tpu_jit_kernel_registered_with_clear_error():
    # On TPU, tf.function(jit_compile=True) around hvd ops must fail at
    # TRACE time with a redirect to the JAX adapter (a host custom-call
    # cannot live in a TPU executable).  No TPU-enabled TF exists in
    # this environment, so assert the XLA_TPU_JIT registration and its
    # message are compiled into the op library; the run-time behavior
    # test below exercises it when a TPU TF is present.
    from horovod_tpu.tensorflow import xla_ops
    assert xla_ops.load() is not None, xla_ops._load_error
    blob = open(xla_ops._LIB, "rb").read()
    assert b"XLA_TPU_JIT" in blob
    assert b"Use the JAX adapter" in blob


@pytest.mark.skipif(
    not any(d.device_type == "TPU"
            for d in __import__("tensorflow").config.list_logical_devices()),
    reason="no TPU-enabled TensorFlow in this environment")
def test_tpu_jit_raises_at_trace_time():
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd
    hvd.init()

    @tf.function(jit_compile=True)
    def step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tpu_jit_ar")

    with tf.device("/device:TPU:0"):
        with pytest.raises(Exception, match="JAX adapter"):
            step(tf.constant([1.0, 2.0]))


@pytest.mark.parametrize("size", [2, 4])
def test_multirank_tape_optimizer_broadcast_compression(size):
    # Real N-process world: DistributedGradientTape averaging,
    # broadcast_variables/broadcast_object, the Keras
    # DistributedOptimizer update, and fp16 wire compression. Closes the
    # round-1 gap of adapters only being wire-tested at size 1.
    import os
    from tests.utils.spawn import spawn_world, assert_world_ok
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "utils", "tf_adapter_worker.py")
    assert_world_ok(spawn_world(worker, size), "TF_ADAPTER_OK")


def test_jit_compile_on_tpu_raises_at_trace_time(hvd, monkeypatch):
    """VERDICT r3 item 6: a host py_function collective cannot live in
    a TPU executable; jit_compile=True tracing on a TPU must raise an
    actionable error redirecting to horovod_tpu.jax — at TRACE time,
    not as an opaque XLA compile failure at step time.  TPU presence
    is forced via the predicate so the contract is covered on CPU; the
    TPU-gated test below exercises the real device enumeration."""
    from horovod_tpu.tensorflow import mpi_ops
    monkeypatch.setattr(mpi_ops, "_TPU_PRESENT", True)

    @tf.function(jit_compile=True)
    def jit_step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tf_jit_tpu")

    with pytest.raises(Exception, match="horovod_tpu.jax"):
        jit_step(tf.ones((4,)))

    @tf.function(jit_compile=True)
    def jit_group(x):
        return hvd.grouped_allreduce([x, x], op=hvd.Sum,
                                     name="tf_jit_tpu_g")

    with pytest.raises(Exception, match="horovod_tpu.jax"):
        jit_group(tf.ones((4,)))

    # Plain tf.function (no jit_compile) must keep tracing and running
    # through the py_function staging even with a TPU present.
    @tf.function
    def graph_step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tf_nojit_tpu")

    out = graph_step(tf.ones((4,)))
    assert np.allclose(out.numpy(), 1.0)


def test_jit_compile_raises_on_any_device(hvd):
    """py_function is unsupported in ANY jit_compile=True executable
    (not just TPU): without a TPU the trace-time error points at the
    native-op knob instead of producing the opaque EagerPyFunc XLA
    compile failure at step time."""
    from horovod_tpu.tensorflow import mpi_ops
    assert mpi_ops._TPU_PRESENT is not True  # CPU CI

    @tf.function(jit_compile=True)
    def jit_step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tf_jit_cpu")

    with pytest.raises(Exception, match="HOROVOD_ENABLE_XLA_OPS"):
        jit_step(tf.ones((4,)))


@pytest.mark.skipif(
    not tf.config.list_logical_devices("TPU"),
    reason="no TF TPU device attached (CPU CI); the forced-predicate "
           "test above covers the contract")
def test_jit_compile_on_real_tpu_raises_at_trace_time(hvd):
    @tf.function(jit_compile=True)
    def jit_step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tf_jit_real_tpu")

    with pytest.raises(Exception, match="horovod_tpu.jax"):
        jit_step(tf.ones((4,)))
