"""Torch adapter tests.

Reference parity: ``test/parallel/test_torch.py`` — collectives, the
DistributedOptimizer gradient hooks, parameter/object broadcast, sync
batch norm, and elastic TorchState, run in a real multi-process world
via the launcher (the single-process cases run a size-1 tcp world).
"""

import os
import subprocess
import sys

import numpy as np
import pytest
import torch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def hvd():
    import horovod_tpu.torch as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


def test_size1_collectives(hvd):
    assert hvd.size() == 1 and hvd.rank() == 0
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allreduce(t, op=hvd.Sum, name="ar")
    assert torch.equal(out, t)
    # In-place variant writes through.
    t2 = torch.ones(3)
    hvd.allreduce_(t2, op=hvd.Average, name="ar2")
    assert torch.equal(t2, torch.ones(3))
    g = hvd.allgather(t, name="ag")
    assert torch.equal(g, t)
    b = hvd.broadcast(t, root_rank=0, name="bc")
    assert torch.equal(b, t)
    assert hvd.poll(hvd.allreduce_async(t, name="h")) in (True, False)


def test_size1_optimizer_matches_plain(hvd):
    torch.manual_seed(0)
    model_a = torch.nn.Linear(4, 2)
    model_b = torch.nn.Linear(4, 2)
    model_b.load_state_dict(model_a.state_dict())
    opt_a = torch.optim.SGD(model_a.parameters(), lr=0.1)
    opt_b = hvd.DistributedOptimizer(
        torch.optim.SGD(model_b.parameters(), lr=0.1),
        named_parameters=model_b.named_parameters())
    x = torch.randn(8, 4)
    for m, o in ((model_a, opt_a), (model_b, opt_b)):
        loss = m(x).pow(2).mean()
        loss.backward()
        o.step()
    for pa, pb in zip(model_a.parameters(), model_b.parameters()):
        assert torch.allclose(pa, pb)


def test_partial_named_parameters_rejected(hvd):
    # Reference parity: a named_parameters that does not cover every
    # optimizer param is rejected at construction — otherwise grouped
    # wire order would fall back to autograd hook order, which is not
    # cross-rank deterministic.
    model = torch.nn.Sequential(torch.nn.Linear(4, 3),
                                torch.nn.Linear(3, 2))
    partial = list(model.named_parameters())[:2]
    with pytest.raises(ValueError, match="not named"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=partial)
    dup = [("w", p) for p in model.parameters()]
    with pytest.raises(ValueError, match="duplicate"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=dup)


def test_compression_roundtrip():
    from horovod_tpu.torch.compression import Compression
    t = torch.randn(5)
    wire, ctx = Compression.fp16.compress(t)
    assert wire.dtype == torch.float16
    back = Compression.fp16.decompress(wire, ctx)
    assert back.dtype == torch.float32
    assert torch.allclose(back, t, atol=1e-3)


def test_broadcast_object_and_state(hvd):
    obj = hvd.broadcast_object({"a": 1}, root_rank=0)
    assert obj == {"a": 1}
    model = torch.nn.Linear(3, 3)
    opt = torch.optim.Adam(model.parameters())
    state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=2)
    w0 = model.weight.detach().clone()
    state.commit()
    with torch.no_grad():
        model.weight.add_(1.0)
    state.epoch = 9
    state.restore()
    assert state.epoch == 2
    assert torch.allclose(model.weight, w0)


# -- multi-process integration ---------------------------------------------

def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HOROVOD_RANK", None)
    return env


def test_torch_two_process_training(tmp_path):
    """2 workers: grads averaged across ranks keep replicas identical;
    sync BN statistics cover the global batch; rank-dependent allreduce
    values check the wire."""
    script = tmp_path / "train.py"
    script.write_text("""
import numpy as np
import torch
import horovod_tpu.torch as hvd

hvd.init()
assert hvd.size() == 2
r = hvd.rank()

# Collective values across the real wire.
out = hvd.allreduce(torch.ones(4) * (r + 1), op=hvd.Sum, name="t")
np.testing.assert_allclose(out.numpy(), 3.0)
g = hvd.allgather(torch.full((1, 2), float(r)), name="g")
np.testing.assert_allclose(g.numpy(), [[0.0, 0.0], [1.0, 1.0]])
# Grouped allreduce negotiates atomically by deterministic auto-names.
outs = hvd.grouped_allreduce(
    [torch.ones(3) * (r + 1), torch.ones(2) * 10 * (r + 1)],
    op=hvd.Sum)
np.testing.assert_allclose(outs[0].numpy(), 3.0)
np.testing.assert_allclose(outs[1].numpy(), 30.0)
# bf16 rides the wire natively.
bf = hvd.allreduce(torch.ones(4, dtype=torch.bfloat16), op=hvd.Sum,
                   name="bf")
assert bf.dtype == torch.bfloat16
np.testing.assert_allclose(bf.float().numpy(), 2.0)

# Distributed optimizer: replicas stay in lockstep.
torch.manual_seed(1234 + r)     # different init per rank
model = torch.nn.Sequential(
    torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
hvd.broadcast_parameters(model.state_dict(), root_rank=0)
opt = hvd.DistributedOptimizer(
    torch.optim.SGD(model.parameters(), lr=0.05),
    named_parameters=model.named_parameters())
torch.manual_seed(99 + r)       # different data per rank
for step in range(3):
    x = torch.randn(6, 4)
    loss = model(x).pow(2).mean()
    loss.backward()
    opt.step()
    opt.zero_grad()
# detach: collectives of requires-grad tensors are differentiable now
# (reference autograd semantics), and this is a plain value check
w = torch.cat([p.detach().flatten() for p in model.parameters()])
peer = hvd.allgather(w.unsqueeze(0), name="weights")
np.testing.assert_allclose(peer[0].numpy(), peer[1].numpy(), atol=1e-6)

# Sync BN over the global batch == local BN over the concatenated batch.
bn = hvd.SyncBatchNorm(3)
bn.train()
torch.manual_seed(7)
full = torch.randn(8, 3)
mine = full[r * 4:(r + 1) * 4].clone().requires_grad_(True)
out = bn(mine)
ref_bn = torch.nn.BatchNorm1d(3)
ref_bn.train()
ref_out = ref_bn(full)
np.testing.assert_allclose(out.detach().numpy(),
                           ref_out[r * 4:(r + 1) * 4].detach().numpy(),
                           atol=1e-5)
out.sum().backward()
ref_full = full.clone().requires_grad_(True)
torch.nn.BatchNorm1d(3).train()(ref_full).sum().backward()
np.testing.assert_allclose(mine.grad.numpy(),
                           ref_full.grad[r * 4:(r + 1) * 4].numpy(),
                           atol=1e-5)

print("TORCH_OK", r, flush=True)
hvd.shutdown()
""")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, env=_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "TORCH_OK 0" in proc.stdout
    assert "TORCH_OK 1" in proc.stdout


WORKER = os.path.join(REPO, "tests", "utils", "torch_adapter_worker.py")


GROUPED_WORKER = os.path.join(REPO, "tests", "utils",
                              "torch_grouped_worker.py")


@pytest.mark.parametrize("size", [2, 4])
def test_multirank_grouped_and_sparse_optimizer(size):
    # num_groups buckets (grouped_allreduce negotiation), explicit
    # groups with ungrouped leftovers, sparse embedding grads, and the
    # differentiable collectives, all against recomputed world oracles.
    from tests.utils.spawn import spawn_world, assert_world_ok
    assert_world_ok(spawn_world(GROUPED_WORKER, size),
                    "TORCH_GROUPED_OK")


@pytest.mark.parametrize("size", [2, 4])
def test_multirank_optimizer_broadcast_compression(size):
    # Real N-process world: DistributedOptimizer averaging (differs from
    # local grads, matches a recomputed world mean), parameter/optimizer
    # state broadcast, and fp16 wire compression. Closes the round-1 gap
    # of adapters only being wire-tested at size 1.
    from tests.utils.spawn import spawn_world, assert_world_ok
    assert_world_ok(spawn_world(WORKER, size), "TORCH_ADAPTER_OK")


def test_dlpack_bridge_and_device_payload_routing(hvd):
    # The dlpack bridge torch->jax works (CPU backends share the
    # buffer semantics the device path relies on)...
    from horovod_tpu.torch.mpi_ops import _device_to_jax, _payload
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    arr = _device_to_jax(t)
    assert np.allclose(np.asarray(arr), t.numpy())
    # ...and CPU tensors still take the zero-copy numpy view.
    view = _payload(t)
    assert isinstance(view, np.ndarray)
    assert view.ctypes.data == t.data_ptr()
    # A collective on the bridged jax payload round-trips through the
    # adapter handle machinery.
    out = hvd.allreduce(t, op=hvd.Sum, name="dlpack_ar")
    assert torch.equal(out, t)
