"""torch_xla zero-copy dlpack bridge (SURVEY §7 "torch_xla bridging").

These run only where torch_xla is installed (it is not baked into this
environment — the skip is the documented gate, see docs/adapters.md);
the bridge glue itself (`_xla_to_jax`, the dlpack return leg in
`TorchHandle._convert`, and the host-materialization fallback) is
exercised structurally below without torch_xla.
"""

import numpy as np
import pytest
import torch

import horovod_tpu.torch.mpi_ops as mpi_ops


def test_bridge_glue_importable_and_fallback_structure():
    # The xla branch must try the dlpack bridge first and only then
    # fall back to host materialization; assert the functions exist and
    # the payload router handles CPU tensors unchanged (zero-copy view).
    assert callable(mpi_ops._xla_to_jax)
    t = torch.arange(6, dtype=torch.float32)
    view = mpi_ops._payload(t)
    assert isinstance(view, np.ndarray)
    # zero-copy: mutating the tensor is visible through the view
    t[0] = 41.0
    assert view[0] == 41.0


torch_xla = pytest.importorskip(
    "torch_xla", reason="torch_xla not installed in this environment "
                        "(documented skip; see docs/adapters.md)")


def test_xla_tensor_allreduce_roundtrip_zero_copy():
    import torch_xla.core.xla_model as xm

    import horovod_tpu.torch as hvd
    hvd.init()
    dev = xm.xla_device()
    x = torch.ones(8, device=dev) * float(hvd.rank() + 1)
    out = hvd.allreduce(x, op=hvd.Sum, name="txla_ar")
    assert out.device.type == "xla"
    expected = sum(r + 1.0 for r in range(hvd.size()))
    np.testing.assert_allclose(out.cpu().numpy(), expected)
