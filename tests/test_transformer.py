"""Flagship transformer tests: dense dp/sp/tp training, MoE variant,
single-device equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from horovod_tpu.models.transformer import (TransformerConfig, forward,
                                            init_params, loss_fn,
                                            make_train_step)

VOCAB = 64


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4,
                n_kv_heads=2, d_ff=64, max_seq=64, dtype="float32")
    base.update(kw)
    return TransformerConfig(**base)


def _mesh(shape, names):
    devs = np.asarray(jax.devices()).reshape(shape)
    return Mesh(devs, names)


def _batch(rng, b, s):
    tokens = rng.randint(0, VOCAB, size=(b, s)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    return {"tokens": tokens, "targets": targets}


def test_dense_transformer_trains_dp_sp_tp(hvd_world):
    cfg = _cfg()
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
    build, shard_batch = make_train_step(cfg, mesh, optax.adam(1e-2))
    params = init_params(jax.random.PRNGKey(0), cfg)
    step, params, opt_state = build(params)
    rng = np.random.RandomState(0)
    batch = shard_batch(_batch(rng, 4, 32))
    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7  # memorizing a fixed batch


def test_moe_transformer_trains(hvd_world):
    cfg = _cfg(n_experts=4, top_k=2, capacity_factor=2.0, d_ff=32)
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
    build, shard_batch = make_train_step(cfg, mesh, optax.adam(1e-2))
    params = init_params(jax.random.PRNGKey(1), cfg)
    step, params, opt_state = build(params)
    rng = np.random.RandomState(1)
    batch = shard_batch(_batch(rng, 4, 32))
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sharded_loss_matches_single_device(hvd_world):
    """Same params/batch: (2,2,2) mesh loss == (1,1,1) mesh loss."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.RandomState(2)
    batch = _batch(rng, 4, 32)

    def run(mesh_shape, names, devices):
        mesh = Mesh(np.asarray(devices).reshape(mesh_shape), names)
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.models.transformer import param_specs
        import jax as _jax
        f = _jax.jit(_jax.shard_map(
            lambda p, b: loss_fn(p, b, cfg), mesh=mesh,
            in_specs=(param_specs(cfg),
                      {"tokens": P("dp", "sp"), "targets": P("dp", "sp")}),
            out_specs=P(), check_vma=False))
        return float(f(params, batch))

    l_multi = run((2, 2, 2), ("dp", "sp", "tp"), jax.devices())
    l_single = run((1, 1, 1), ("dp", "sp", "tp"), jax.devices()[:1])
    assert l_multi == pytest.approx(l_single, rel=2e-4)


def test_remat_matches_no_remat(hvd_world):
    cfg = _cfg(remat=True)
    cfg_plain = _cfg(remat=False)
    params = init_params(jax.random.PRNGKey(3), cfg_plain)
    rng = np.random.RandomState(3)
    batch = _batch(rng, 2, 16)
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.models.transformer import param_specs

    def gradnorm(c):
        f = jax.jit(jax.shard_map(
            jax.grad(lambda p, b: loss_fn(p, b, c)), mesh=mesh,
            in_specs=(param_specs(c),
                      {"tokens": P("dp", "sp"), "targets": P("dp", "sp")}),
            out_specs=param_specs(c), check_vma=False))
        g = f(params, batch)
        return float(optax.global_norm(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), g)))

    np.testing.assert_allclose(gradnorm(cfg), gradnorm(cfg_plain),
                               rtol=1e-4)


def test_split_optimizer_matches_fused_step(hvd_world):
    """The split-two-programs anti-lever (backward and optimizer
    update jitted separately) must produce the same loss and params as
    the fused step on a real dp/sp/tp mesh — otherwise the fusion A/B
    it exists for measures diverged math, not program structure."""
    cfg = _cfg()
    params_host = init_params(jax.random.PRNGKey(9), cfg)
    rng = np.random.RandomState(9)
    batch_np = _batch(rng, 4, 16)
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))

    def run(split):
        build, shard_batch = make_train_step(
            cfg, mesh, optax.adam(1e-2), donate=False,
            split_optimizer=split)
        step, params, opt_state = build(params_host)
        loss = None
        for _ in range(2):
            params, opt_state, loss = step(
                params, opt_state, shard_batch(batch_np))
        pn = float(optax.global_norm(jax.tree.map(
            lambda x: jnp.asarray(x, jnp.float32), params)))
        return float(loss), pn

    l_f, p_f = run(False)
    l_s, p_s = run(True)
    np.testing.assert_allclose(l_s, l_f, rtol=1e-5)
    np.testing.assert_allclose(p_s, p_f, rtol=1e-5)


def test_collective_matmul_matches_psum(hvd_world):
    """The latency-hiding TP matmul ring (collective_matmul=True wires
    parallel/collective_matmul.py into the wo / w2 row-parallel
    products) must be numerically exact vs the plain psum form, for
    loss AND gradients, on a real tp>1 mesh (VERDICT r4 Next #3: the
    component stops being dead inventory)."""
    cfg = _cfg(collective_matmul=True)
    cfg_plain = _cfg(collective_matmul=False)
    params = init_params(jax.random.PRNGKey(7), cfg_plain)
    rng = np.random.RandomState(7)
    batch = _batch(rng, 4, 16)
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.models.transformer import param_specs

    def loss_and_gradnorm(c):
        bspec = {"tokens": P("dp", "sp"), "targets": P("dp", "sp")}
        f = jax.jit(jax.shard_map(
            jax.value_and_grad(lambda p, b: loss_fn(p, b, c)),
            mesh=mesh, in_specs=(param_specs(c), bspec),
            out_specs=(P(), param_specs(c)), check_vma=True))
        loss, g = f(params, batch)
        return float(loss), float(optax.global_norm(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), g)))

    l_cm, g_cm = loss_and_gradnorm(cfg)
    l_ps, g_ps = loss_and_gradnorm(cfg_plain)
    np.testing.assert_allclose(l_cm, l_ps, rtol=1e-5)
    np.testing.assert_allclose(g_cm, g_ps, rtol=1e-4)


def test_sharded_gradients_match_single_device(hvd_world):
    """Loss AND gradients must be mesh-invariant under the vma-tracked
    step (r4: the previous check_vma=False form psum'ed grads over
    (dp, sp) on top of already-combined cotangents, scaling updates by
    dp*sp — this is the regression guard)."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(5), cfg)
    rng = np.random.RandomState(5)
    batch = _batch(rng, 4, 16)
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.models.transformer import param_specs

    def loss_and_gradnorm(mesh):
        bspec = {"tokens": P("dp", "sp"), "targets": P("dp", "sp")}
        f = jax.jit(jax.shard_map(
            jax.value_and_grad(lambda p, b: loss_fn(p, b, cfg)),
            mesh=mesh, in_specs=(param_specs(cfg), bspec),
            out_specs=(P(), param_specs(cfg)), check_vma=True))
        loss, g = f(params, batch)
        return float(loss), float(optax.global_norm(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), g)))

    l1, g1 = loss_and_gradnorm(
        Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
             ("dp", "sp", "tp")))
    l8, g8 = loss_and_gradnorm(_mesh((2, 2, 2), ("dp", "sp", "tp")))
    np.testing.assert_allclose(l8, l1, rtol=1e-5)
    np.testing.assert_allclose(g8, g1, rtol=1e-4)


def test_fused_projections_match_unfused(hvd_world):
    """fused_qkv/fused_gate only repack the per-shard weight slices —
    loss and gradients must be identical to the three-matmul form,
    including under tp sharding (the local-boundary split)."""
    cfg_f = _cfg(fused_qkv=True, fused_gate=True)
    cfg_u = _cfg(fused_qkv=False, fused_gate=False)
    params = init_params(jax.random.PRNGKey(7), cfg_u)
    rng = np.random.RandomState(7)
    batch = _batch(rng, 2, 16)
    mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.models.transformer import param_specs

    def loss_and_gradnorm(c):
        f = jax.jit(jax.shard_map(
            jax.value_and_grad(lambda p, b: loss_fn(p, b, c)),
            mesh=mesh,
            in_specs=(param_specs(c),
                      {"tokens": P("dp", "sp"), "targets": P("dp", "sp")}),
            out_specs=(P(), param_specs(c)), check_vma=False))
        loss, g = f(params, batch)
        return float(loss), float(optax.global_norm(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), g)))

    lf, gf = loss_and_gradnorm(cfg_f)
    lu, gu = loss_and_gradnorm(cfg_u)
    np.testing.assert_allclose(lf, lu, rtol=1e-6)
    np.testing.assert_allclose(gf, gu, rtol=1e-5)


def test_ulysses_sp_matches_ring(hvd_world):
    # same model, same batch: ulysses (alltoall head exchange) must
    # produce the same loss surface as ring SP. heads=4 % sp=2 == 0.
    rng = np.random.RandomState(3)
    batch_host = _batch(rng, 4, 32)
    losses = {}
    for mode in ("ring", "ulysses"):
        cfg = _cfg(n_kv_heads=4, sp_mode=mode)
        mesh = _mesh((2, 2, 2), ("dp", "sp", "tp"))
        build, shard_batch = make_train_step(cfg, mesh,
                                             optax.sgd(1e-2))
        params = init_params(jax.random.PRNGKey(0), cfg)
        step, params, opt_state = build(params)
        batch = shard_batch(batch_host)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, batch)
        losses[mode] = float(loss)
    assert np.isclose(losses["ring"], losses["ulysses"],
                      rtol=1e-4), losses
