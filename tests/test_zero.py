"""ZeRO-1/2/3 sharded-training-state tests (8-device CPU world):
numerics vs single-device (position-dependent payloads), sharded state
placement, the quantized proc×local DCN leg within EF bounds, the HLO
span assert, the non-elementwise guard, and stage dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from horovod_tpu.jax.zero import (make_zero1_step, make_zero2_step,
                                  make_zero3_step, make_zero_step,
                                  zero_stage_from_env)


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(7, 3), jnp.float32)   # 21 elems: ragged
    b = jnp.asarray(rng.randn(3), jnp.float32)
    x = jnp.asarray(rng.randn(32, 7), jnp.float32)
    y = jnp.asarray(rng.randn(32, 3), jnp.float32)

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return {"w": w, "b": b}, {"x": x, "y": y}, loss_fn


def test_zero1_matches_unsharded_adam(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem()
    opt = optax.adam(1e-2)

    # reference: plain replicated training on the same global batch
    ref_params = params
    ref_state = opt.init(ref_params)

    def ref_step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p, batch)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    step, init = make_zero1_step(loss_fn, optax.adam(1e-2))
    z_params = hvd.replicate(params)
    z_state = init(z_params)
    z_batch = hvd.shard_batch(batch)

    for _ in range(5):
        ref_params, ref_state, ref_loss = ref_step(ref_params,
                                                   ref_state)
        z_params, z_state, z_loss = step(z_params, z_state, z_batch)

    np.testing.assert_allclose(float(z_loss), float(ref_loss),
                               rtol=1e-4)
    for k in params:
        np.testing.assert_allclose(np.asarray(z_params[k]),
                                   np.asarray(ref_params[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero1_state_is_sharded(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(1)
    step, init = make_zero1_step(loss_fn, optax.adam(1e-2))
    z_params = hvd.replicate(params)
    state = init(z_params)
    n = len(jax.devices())
    # adam's mu for 'w' (21 elems padded to 24): global dim is n shards
    mu_w = state[0].mu["w"]
    per = -(-21 // n)  # ceil
    assert mu_w.shape[0] == n * per, mu_w.shape
    # and it is actually distributed, not replicated
    assert len(mu_w.sharding.device_set) == n


def test_zero1_requires_init_first(hvd_world):
    params, batch, loss_fn = _problem(2)
    step, init = make_zero1_step(loss_fn, optax.sgd(0.1))
    with pytest.raises(RuntimeError):
        step(params, None, batch)


def _reference(params, batch, loss_fn, opt, steps, every=1):
    """Single-device adam trajectory: update applied once per `every`
    micro-steps (grad accumulation of identical microbatches)."""
    p, s = params, opt.init(params)
    for i in range(steps):
        if (i + 1) % every == 0:
            loss, g = jax.value_and_grad(loss_fn)(p, batch)
            u, s = opt.update(g, s, p)
            p = optax.apply_updates(p, u)
    return p


def _two_level_mesh():
    devs = np.array(jax.devices())
    return Mesh(devs.reshape(2, devs.size // 2), ("proc", "local"))


def test_zero2_matches_unsharded_adam(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(3)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 5)
    step, init = make_zero2_step(loss_fn, optax.adam(1e-2))
    zp = hvd.replicate(params)
    carry = init(zp)
    zb = hvd.shard_batch(batch)
    for _ in range(5):
        zp, carry, zl = step(zp, carry, zb)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]),
                                   np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero2_accum_shards_are_persistent_and_sharded(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(4)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 6, every=2)
    step, init = make_zero2_step(loss_fn, optax.adam(1e-2),
                                 accum_steps=2)
    zp = hvd.replicate(params)
    carry = init(zp)
    zb = hvd.shard_batch(batch)
    n = len(jax.devices())
    # the persistent gradient state is a 1/n shard per device
    for name, acc in carry["acc"].items():
        assert len(acc.sharding.device_set) == n, name
    for _ in range(6):
        zp, carry, _ = step(zp, carry, zb)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]),
                                   np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero1_accum_keeps_replicated_gradient_layout(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(5)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 4, every=2)
    step, init = make_zero1_step(loss_fn, optax.adam(1e-2),
                                 accum_steps=2)
    zp = hvd.replicate(params)
    carry = init(zp)
    zb = hvd.shard_batch(batch)
    # stage-1 gradient layout: accumulator FULL and replicated
    _opt, acc, _micro = carry
    assert acc["w"].shape == params["w"].shape
    for _ in range(4):
        zp, carry, _ = step(zp, carry, zb)
    for k in params:
        np.testing.assert_allclose(np.asarray(zp[k]),
                                   np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero3_matches_unsharded_and_state_is_sharded(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(6)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 5)
    step, init, gather = make_zero3_step(loss_fn, optax.adam(1e-2))
    state = init(hvd.replicate(params))
    n = len(jax.devices())
    # params themselves live sharded (THE stage-3 property)
    for name, shard in state["shards"].items():
        assert len(shard.sharding.device_set) == n, name
    zb = hvd.shard_batch(batch)
    for _ in range(5):
        state, _ = step(state, zb)
    full = gather(state)
    for k in params:
        np.testing.assert_allclose(np.asarray(full[k]),
                                   np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero2_quantized_leg_within_ef_bounds(hvd_world):
    """int8 DCN leg over the explicit (2, 4) proc×local mesh:
    position-dependent payloads, trajectory within the quantization
    bound of the exact run, EF residuals present and carried."""
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(7)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 5)
    step, init = make_zero2_step(loss_fn, optax.adam(1e-2),
                                 mesh=_two_level_mesh(),
                                 axes=("proc", "local"), wire="int8")
    zp = hvd.replicate(params)
    carry = init(zp)
    assert carry["ef"], "per-tensor EF residuals missing"
    zb = hvd.shard_batch(batch)
    for _ in range(5):
        zp, carry, _ = step(zp, carry, zb)
    for k in params:
        err = np.max(np.abs(np.asarray(zp[k]) - np.asarray(ref[k])))
        assert err < 5e-3, (k, err)
    # the residual is live state, not zeros (EF is actually engaged)
    assert any(float(np.max(np.abs(np.asarray(r)))) > 0
               for r in carry["ef"].values())


def test_zero3_quantized_gather_master_stays_clean(hvd_world):
    """int8 param gather-on-demand: per-step noise is bounded and the
    MASTER shards track the exact trajectory closely (gather noise is
    transient, never integrated into the shards)."""
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(8)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 5)
    step, init, gather = make_zero3_step(loss_fn, optax.adam(1e-2),
                                         mesh=_two_level_mesh(),
                                         axes=("proc", "local"),
                                         wire="int8")
    state = init(hvd.replicate(params))
    zb = hvd.shard_batch(batch)
    for _ in range(5):
        state, _ = step(state, zb)
    full = gather(state)
    for k in params:
        err = np.max(np.abs(np.asarray(full[k]) - np.asarray(ref[k])))
        assert err < 2e-2, (k, err)


def _compiled_hlo(step, *args):
    """HLO text of the step's compiled executable (the step wrapper
    closes over its ``compiled`` dict of jitted fns)."""
    for cell in step.__closure__ or ():
        val = cell.cell_contents
        if isinstance(val, dict) and "step" in val:
            return val["step"].lower(*args).compile().as_text()
    raise AssertionError("compiled step not found in closure")


def test_zero2_hlo_spans_proc_times_local(hvd_world):
    """The lowered step is ONE program over all proc×local partitions
    with real reduce-scatter/all-gather collective HLO (the structural
    half of the 2-proc e2e's span assert)."""
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(9)
    step, init = make_zero2_step(loss_fn, optax.adam(1e-2),
                                 mesh=_two_level_mesh(),
                                 axes=("proc", "local"), wire="int8")
    zp = hvd.replicate(params)
    carry = init(zp)
    zb = hvd.shard_batch(batch)
    n_total = len(jax.devices())
    exe_txt = _compiled_hlo(step, zp, carry, zb)
    assert "num_partitions = %d" % n_total in exe_txt \
        or "num_partitions=%d" % n_total in exe_txt, \
        "step program does not span all %d devices" % n_total
    assert "reduce-scatter" in exe_txt or "reduce_scatter" in exe_txt
    assert "all-gather" in exe_txt or "all_gather" in exe_txt


def test_non_elementwise_optimizers_refused():
    bad = [optax.chain(optax.clip_by_global_norm(1.0),
                       optax.sgd(0.1))]
    if hasattr(optax, "lamb"):
        bad.append(optax.lamb(1e-3))
    if hasattr(optax, "adafactor"):
        bad.append(optax.adafactor(1e-3))
    params, batch, loss_fn = _problem()
    for opt in bad:
        for build in (make_zero1_step,
                      make_zero2_step,
                      lambda l, o: make_zero3_step(l, o)):
            with pytest.raises(ValueError, match="non-elementwise"):
                build(loss_fn, opt)


def test_make_zero_step_env_dispatch(hvd_world, monkeypatch):
    params, batch, loss_fn = _problem()
    monkeypatch.setenv("HOROVOD_ZERO_STAGE", "2")
    assert zero_stage_from_env() == 2
    out = make_zero_step(loss_fn, optax.adam(1e-2))
    assert len(out) == 2
    out3 = make_zero_step(loss_fn, optax.adam(1e-2), stage=3)
    assert len(out3) == 3
    monkeypatch.setenv("HOROVOD_ZERO_STAGE", "5")
    with pytest.raises(ValueError, match="ZERO_STAGE"):
        zero_stage_from_env()
    monkeypatch.setenv("HOROVOD_ZERO_STAGE", "0")
    out0 = make_zero_step(loss_fn, optax.adam(1e-2))
    assert len(out0) == 2


def test_make_zero_step_stage0_respects_accum_and_refuses_stage23_args(
        hvd_world, monkeypatch):
    """Review regressions: stage 0 must not silently drop accum_steps
    (one update per accum, like stages 1-3 — via MultiSteps), and
    stage-2/3-only arguments are refused at stages 0/1 instead of
    being ignored under an env flip."""
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(11)
    opt = optax.adam(1e-2)
    ref = _reference(params, batch, loss_fn, opt, 4, every=2)
    monkeypatch.setenv("HOROVOD_ZERO_STAGE", "0")
    step, init = make_zero_step(loss_fn, optax.adam(1e-2),
                                accum_steps=2)
    p = hvd.replicate(params)
    carry = init(p)
    zb = hvd.shard_batch(batch)
    for _ in range(4):
        p, carry, _ = step(p, carry, zb)
    for k in params:
        np.testing.assert_allclose(np.asarray(p[k]),
                                   np.asarray(ref[k]),
                                   atol=1e-5, rtol=1e-4)
    for stage in (0, 1):
        with pytest.raises(ValueError, match="stage-2/3"):
            make_zero_step(loss_fn, optax.adam(1e-2), stage=stage,
                           wire="int8")


def test_wire_resolver_is_the_engine_resolver():
    """One knob, one parser: names the engine's resolver rejects must
    be rejected here too (the planes may never drift on what
    HOROVOD_CROSS_HOST_COMPRESSION means)."""
    from horovod_tpu.jax.zero import _resolve_wire
    assert _resolve_wire("none") is None
    assert _resolve_wire("int8")[2] == "int8"
    assert _resolve_wire("bf16")[0] == "cast"
    with pytest.raises(ValueError):
        _resolve_wire("float16")  # engine spelling is 'fp16'


def test_explicit_wire_without_cross_host_leg_is_refused(hvd_world,
                                                         monkeypatch):
    """Review regressions: an explicit wire= on a mesh with no DCN leg
    raises (silent full-precision would misattribute results); an
    env-derived codec only warns; negative/malformed
    HOROVOD_ZERO_STAGE values are refused loudly, not clamped."""
    params, batch, loss_fn = _problem(12)
    with pytest.raises(ValueError, match="no.*cross-host leg|cross-host"):
        make_zero2_step(loss_fn, optax.adam(1e-2), wire="int8")
    # env-derived codec degrades with a warning, not an error
    monkeypatch.setenv("HOROVOD_CROSS_HOST_COMPRESSION", "int8")
    step, init = make_zero2_step(loss_fn, optax.adam(1e-2))
    assert step is not None
    monkeypatch.delenv("HOROVOD_CROSS_HOST_COMPRESSION")
    for bad in ("-1", "two"):
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", bad)
        with pytest.raises(ValueError, match="HOROVOD_ZERO_STAGE"):
            zero_stage_from_env()
