"""ZeRO-1 sharded-optimizer tests (8-device CPU world)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.jax.zero import make_zero1_step


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(7, 3), jnp.float32)   # 21 elems: ragged
    b = jnp.asarray(rng.randn(3), jnp.float32)
    x = jnp.asarray(rng.randn(32, 7), jnp.float32)
    y = jnp.asarray(rng.randn(32, 3), jnp.float32)

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return {"w": w, "b": b}, {"x": x, "y": y}, loss_fn


def test_zero1_matches_unsharded_adam(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem()
    opt = optax.adam(1e-2)

    # reference: plain replicated training on the same global batch
    ref_params = params
    ref_state = opt.init(ref_params)

    def ref_step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p, batch)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    step, init = make_zero1_step(loss_fn, optax.adam(1e-2))
    z_params = hvd.replicate(params)
    z_state = init(z_params)
    z_batch = hvd.shard_batch(batch)

    for _ in range(5):
        ref_params, ref_state, ref_loss = ref_step(ref_params,
                                                   ref_state)
        z_params, z_state, z_loss = step(z_params, z_state, z_batch)

    np.testing.assert_allclose(float(z_loss), float(ref_loss),
                               rtol=1e-4)
    for k in params:
        np.testing.assert_allclose(np.asarray(z_params[k]),
                                   np.asarray(ref_params[k]),
                                   atol=1e-5, rtol=1e-4)


def test_zero1_state_is_sharded(hvd_world):
    import horovod_tpu.jax as hvd
    params, batch, loss_fn = _problem(1)
    step, init = make_zero1_step(loss_fn, optax.adam(1e-2))
    z_params = hvd.replicate(params)
    state = init(z_params)
    n = len(jax.devices())
    # adam's mu for 'w' (21 elems padded to 24): global dim is n shards
    mu_w = state[0].mu["w"]
    per = -(-21 // n)  # ceil
    assert mu_w.shape[0] == n * per, mu_w.shape
    # and it is actually distributed, not replicated
    assert len(mu_w.sharding.device_set) == n


def test_zero1_requires_init_first(hvd_world):
    params, batch, loss_fn = _problem(2)
    step, init = make_zero1_step(loss_fn, optax.sgd(0.1))
    with pytest.raises(RuntimeError):
        step(params, None, batch)
