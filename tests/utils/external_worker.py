"""Worker exercising the core's external-payload (device collective)
protocol: enqueue negotiation-only ops, drain negotiated group records,
and check every rank observes the SAME execution order — the contract the
multihost XLA executor depends on (reference analog: the MPI-control /
NCCL-payload split, SURVEY.md §2.6)."""

import ctypes
import os
import struct
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

from horovod_tpu.common.topology import multiprocess_topology
from horovod_tpu.common.config import Config
from horovod_tpu.core.client import TcpCore, parse_negotiated_record


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    topo = multiprocess_topology(rank, size)
    core = TcpCore(topo, Config.from_env())
    core.initialize()
    try:
        scenario = os.environ.get("TEST_SCENARIO", "order")
        if scenario == "order":
            run_order(core, rank, size)
        elif scenario == "mixed":
            run_mixed(core, rank, size)
    finally:
        core.shutdown()


def drain_groups(core, expect_entries, timeout=30.0):
    """Collect negotiated group records until expect_entries handles seen."""
    import time
    groups = []
    seen = 0
    deadline = time.monotonic() + timeout
    while seen < expect_entries:
        rec = core.next_negotiated()
        if rec is None:
            assert time.monotonic() < deadline, \
                "timed out draining negotiated groups (%d/%d)" % (
                    seen, expect_entries)
            time.sleep(0.002)
            continue
        g = parse_negotiated_record(rec)
        groups.append(g)
        seen += len(g["entries"])
    return groups


def run_order(core, rank, size):
    # Enqueue external allreduces in rank-dependent wall order (rank r
    # delays differently) — negotiation must still deliver ONE global
    # order, identical across ranks.
    import time
    handles = {}
    names = ["x.%d" % i for i in range(6)]
    for i, n in enumerate(names):
        if rank % 2 == 1:
            time.sleep(0.01 * (6 - i))
        h = core.enqueue_external(
            n, "allreduce", shape=(4 + i,), dtype=np.float32)
        handles[n] = h
    groups = drain_groups(core, len(names))
    flat = [e["name"] for g in groups for e in g["entries"]]
    assert sorted(flat) == sorted(names), flat
    # Report the observed order through a REAL collective so ranks can
    # cross-check: allgather the order string and compare.
    order_blob = np.frombuffer(",".join(flat).encode(), dtype=np.uint8)
    gathered = core.allgather_async(order_blob, "order_check").wait(30)
    text = bytes(np.asarray(gathered).tobytes()).decode()
    mine = ",".join(flat)
    assert text == mine * size, (mine, text)
    # Groups carry metadata + handles; complete them.
    for g in groups:
        assert g["op_type"] == "allreduce"
        assert g["dtype"] == np.dtype("float32")
        for e in g["entries"]:
            assert e["handle"] == handles[e["name"]]._h
            core.external_done(e["handle"], ok=True)
    for n in names:
        # Completes without error; external ops carry no host payload
        # (the device result lives with the executor).
        handles[n].wait(timeout=30)
    print("ORDER_OK", rank)


def run_mixed(core, rank, size):
    # External and host-payload allreduces interleave but never fuse
    # together; host ops still move bytes through the CPU rings.
    hx = core.enqueue_external("dev.a", "allreduce", shape=(8,),
                               dtype=np.float32)
    arr = np.full((8,), float(rank + 1), np.float32)
    hh = core.allreduce_async(arr, "host.a")
    groups = drain_groups(core, 1)
    (g,) = groups
    assert [e["name"] for e in g["entries"]] == ["dev.a"]
    core.external_done(g["entries"][0]["handle"], ok=True)
    hx.wait(30)
    out = hh.wait(30)
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    # An external op can also FAIL from the executor; the error must
    # surface through the handle.
    hx2 = core.enqueue_external("dev.fail", "allreduce", shape=(2,),
                                dtype=np.float32)
    (g2,) = drain_groups(core, 1)
    core.external_done(g2["entries"][0]["handle"], ok=False,
                       error="device exploded")
    try:
        hx2.wait(30)
        raise AssertionError("expected HorovodInternalError")
    except Exception as e:  # HorovodInternalError
        assert "device exploded" in str(e)
    print("MIXED_OK", rank)


if __name__ == "__main__":
    main()
