"""Trace-replay fakes for platforms absent from this environment.

pyspark and ray cannot be installed here (no network), so these
modules implement the EXACT API surfaces ``horovod_tpu.spark.run`` and
``horovod_tpu.ray.RayExecutor`` call — recorded from the real
platforms — with real child PROCESSES behind them, so the framework
code runs unchanged end to end (barrier tasks / actors get isolated
environments, the user fn can bootstrap a real hvd TCP world through
the rendezvous server the platform glue started).  A future
environment with the real dependencies runs the same framework code
with zero changes — that is the contract.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import sys
import types
from typing import Any, Dict, List

import cloudpickle

_CTX = mp.get_context("spawn")
_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# fake pyspark: SparkContext + barrier RDD + BarrierTaskContext
# ---------------------------------------------------------------------------


def _spark_task(rank: int, n: int, blob: bytes, barrier, queue):
    """One barrier task in its own process (real Spark runs tasks in
    executor JVM-forked python workers; process isolation is the part
    that matters: per-task os.environ)."""
    sys.path.insert(0, _REPO)

    class TaskInfo:
        def __init__(self, address):
            self.address = address

    class BarrierTaskContext:
        @classmethod
        def get(cls):
            return cls._instance

        def partitionId(self):  # noqa: N802 - pyspark API
            return rank

        def getTaskInfos(self):  # noqa: N802 - pyspark API
            return [TaskInfo("127.0.0.1:%d" % (36000 + i))
                    for i in range(n)]

        def barrier(self):
            barrier.wait()

    BarrierTaskContext._instance = BarrierTaskContext()
    fake = types.ModuleType("pyspark")
    fake.BarrierTaskContext = BarrierTaskContext
    sys.modules["pyspark"] = fake
    mapper = cloudpickle.loads(blob)
    try:
        out = list(mapper(iter([rank])))
        queue.put((rank, out, None))
    except Exception as exc:  # noqa: BLE001 - report to the driver
        queue.put((rank, None, "%s: %s" % (type(exc).__name__, exc)))


class _FakeRDD:
    def __init__(self, num_partitions: int):
        self._n = num_partitions
        self._mapper = None

    def barrier(self):
        return self

    def mapPartitions(self, mapper):  # noqa: N802 - pyspark API
        self._mapper = mapper
        return self

    def collect(self) -> List[Any]:
        blob = cloudpickle.dumps(self._mapper)
        barrier = _CTX.Barrier(self._n)
        queue = _CTX.Queue()
        procs = [_CTX.Process(target=_spark_task,
                              args=(r, self._n, blob, barrier, queue))
                 for r in range(self._n)]
        for p in procs:
            p.start()
        results = []
        for _ in range(self._n):
            rank, out, err = queue.get(timeout=180)
            if err is not None:
                for p in procs:
                    p.terminate()
                raise RuntimeError("task %d failed: %s" % (rank, err))
            results.extend(out)
        for p in procs:
            p.join(timeout=30)
        return results


class FakeSparkContext:
    _active_spark_context = None

    def __init__(self, parallelism: int = 2):
        self.defaultParallelism = parallelism
        FakeSparkContext._active_spark_context = self

    def parallelize(self, data, num_partitions):
        return _FakeRDD(num_partitions)

    def stop(self):
        FakeSparkContext._active_spark_context = None


def install_fake_pyspark(monkeypatch, parallelism: int = 2):
    """sys.modules['pyspark'] speaking the recorded driver-side API."""
    fake = types.ModuleType("pyspark")
    fake.SparkContext = FakeSparkContext
    monkeypatch.setitem(sys.modules, "pyspark", fake)
    return FakeSparkContext(parallelism)


# ---------------------------------------------------------------------------
# fake ray: remote actor classes on real child processes
# ---------------------------------------------------------------------------


def _actor_server(cls_blob: bytes, conn):
    """Actor loop: instantiate the shipped class, serve method calls."""
    sys.path.insert(0, _REPO)
    _install_fake_ray_child()
    cls = cloudpickle.loads(cls_blob)
    inst = cls()
    while True:
        try:
            msg = conn.recv_bytes()
        except EOFError:
            break
        method, args, kwargs = cloudpickle.loads(msg)
        if method == "__stop__":
            break
        try:
            out = getattr(inst, method)(*args, **(kwargs or {}))
            conn.send_bytes(cloudpickle.dumps(("ok", out)))
        except Exception as exc:  # noqa: BLE001 - report to driver
            conn.send_bytes(cloudpickle.dumps(
                ("err", "%s: %s" % (type(exc).__name__, exc))))


def _install_fake_ray_child():
    """Inside an actor process: `import ray` must resolve (actors call
    ray.util.get_node_ip_address)."""
    fake = types.ModuleType("ray")
    util_mod = types.ModuleType("ray.util")
    util_mod.get_node_ip_address = lambda: "127.0.0.1"
    fake.util = util_mod
    sys.modules["ray"] = fake
    sys.modules["ray.util"] = util_mod


class FakeRayError(Exception):
    """Stands in for ray.exceptions.RayError: actor-side exceptions
    surface from ray.get as a RayError subclass on real ray, and the
    elastic executor's retry logic keys on that type."""


class _Future:
    """Dispatched at .remote() time (like real ray) so concurrent
    actor calls — e.g. a blocking collective world — actually overlap;
    resolution reads the reply (per-actor pipe order = call order)."""

    def __init__(self, actor):
        self._actor = actor

    def _resolve(self):
        status, out = cloudpickle.loads(self._actor._conn.recv_bytes())
        if status != "ok":
            raise FakeRayError(out)
        return out


class _BoundMethod:
    def __init__(self, actor, name):
        self._actor = actor
        self._name = name

    def remote(self, *args, **kwargs):
        self._actor._conn.send_bytes(cloudpickle.dumps(
            (self._name, args, kwargs)))
        return _Future(self._actor)


class _ActorHandle:
    def __init__(self, cls):
        self._proc_conn, child_conn = _CTX.Pipe()
        self._conn = self._proc_conn
        self._proc = _CTX.Process(
            target=_actor_server,
            args=(cloudpickle.dumps(cls), child_conn))
        self._proc.start()

    def __getattr__(self, name):
        return _BoundMethod(self, name)


class _RemoteClass:
    def __init__(self, cls):
        self._cls = cls

    def remote(self, *args, **kwargs):
        return _ActorHandle(self._cls)

    def options(self, **kwargs):
        return self


def make_fake_ray(monkeypatch):
    """sys.modules['ray'] with the recorded RayExecutor surface:
    ray.remote / .options().remote() / method .remote() futures /
    ray.get / ray.kill / ray.util.get_node_ip_address.  No
    ray.util.scheduling_strategies, so RayExecutor takes its documented
    plain-scheduling fallback (the placement-group plan math is
    unit-tested separately)."""
    fake = types.ModuleType("ray")
    util_mod = types.ModuleType("ray.util")
    util_mod.get_node_ip_address = lambda: "127.0.0.1"
    fake.util = util_mod

    def remote(*args, **kwargs):
        if len(args) == 1 and isinstance(args[0], type):
            return _RemoteClass(args[0])
        return lambda cls: _RemoteClass(cls)

    def get(futures, timeout=None):
        if isinstance(futures, list):
            return [f._resolve() for f in futures]
        return futures._resolve()

    def kill(actor):
        try:
            actor._proc.terminate()
            actor._proc.join(timeout=10)
        except Exception:  # noqa: BLE001 - already dead
            pass

    exceptions_mod = types.ModuleType("ray.exceptions")
    exceptions_mod.RayError = FakeRayError
    fake.exceptions = exceptions_mod

    fake.remote = remote
    fake.get = get
    fake.kill = kill
    monkeypatch.setitem(sys.modules, "ray", fake)
    monkeypatch.setitem(sys.modules, "ray.util", util_mod)
    monkeypatch.setitem(sys.modules, "ray.exceptions", exceptions_mod)
    return fake
