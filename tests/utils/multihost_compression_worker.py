"""Worker for the cross-host wire-compression e2e: a 2-proc x k-local
multihost world with ``HOROVOD_CROSS_HOST_COMPRESSION`` active runs all
five hierarchical collectives above the threshold, asserting

* numerics within the quantization error bounds (position-dependent
  payloads, so a chunk delivered to the wrong slot fails numerically);
* the WIRE accounting: ``mh_bus_bytes_total`` records compressed bytes
  (>= 3.5x below the payload bytes for int8 — the ISSUE 7 acceptance
  assertion), ``mh_compression_ratio`` / ``mh_compressed_collectives_total``
  register the codec;
* sub-threshold payloads stay on the flat plane, uncompressed and exact;
* device payloads never transit the host (the residency contract holds
  through the eager quantize seam);
* with HVD_TPU_DUMP_HLO=1, the compiled hier programs genuinely carry
  the wire dtype (``s8``) on the cross-host leg.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "4")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.metrics import series_sum as _series_sum


def main():
    codec = os.environ.get("HOROVOD_CROSS_HOST_COMPRESSION", "none")
    assert codec == "int8", "this worker exercises the int8 wire"
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    k = int(os.environ.get("TEST_LOCAL_DEVICES", "4"))

    from horovod_tpu.common import basics
    mc = basics._get_mh_engine().collectives_for(0)
    assert mc._codec is not None and mc._codec.name == "int8", mc._codec

    # -- allreduce (reduce op: error feedback + quantized wire) --------
    big_n = 262144  # 1 MiB f32, far above the 64 KiB hier threshold
    base = np.linspace(-1.0, 1.0, big_n).astype(np.float32)
    expected = base * sum(j + 1.0 for j in range(n))
    # Two-phase quantized allreduce: leg-1 error is bounded by
    # sum_r(absmax_r)/254 per element (per-rank absmax is r+1), leg-2
    # (requantized reduced slice, absmax = sum_r(r+1)) adds the same
    # bound again.
    tol = 2 * sum((j + 1.0) for j in range(n)) / 254.0 * 1.05 + 1e-6
    bus_before = _series_sum("mh_bus_bytes_total", op="allreduce",
                             path="hier")
    out = hvd.allreduce(jnp.asarray(base * (r + 1)), op=hvd.Sum,
                        name="c_ar")
    assert isinstance(out, jax.Array), type(out)
    np.testing.assert_allclose(np.asarray(out), expected, atol=tol)

    # -- the acceptance assertion: wire bytes, not payload bytes -------
    wire_delta = _series_sum("mh_bus_bytes_total", op="allreduce",
                             path="hier") - bus_before
    payload_bytes = big_n * 4
    assert 0 < wire_delta <= payload_bytes / 3.5, (
        "mh_bus_bytes_total recorded %s for a %d-byte payload — not "
        "wire bytes (expected <= %d)" % (
            wire_delta, payload_bytes, payload_bytes // 4 + 4 * k))
    ratio = _series_sum("mh_compression_ratio", op="allreduce",
                        codec="int8")
    assert ratio >= 3.5, "mh_compression_ratio %s < 3.5" % ratio
    assert _series_sum("mh_compressed_collectives_total",
                       op="allreduce", codec="int8") >= 1
    # Error-feedback residual parked for the next step of this bucket.
    assert mc._ef is not None and len(mc._ef._residuals) >= 1

    # Error feedback across steps: repeating the same allreduce folds
    # each step's quantization error into the next — BOTH legs carry a
    # residual (eager per-chunk for contributions, in-program for the
    # requantized reduced slice) — so the MEAN of many steps converges
    # on the true sum far tighter than any single quantized step (the
    # EF telescoping property, observable e2e).  Residuals key by the
    # tensor NAME (per-tensor EF, not cross-tensor), so the step loop
    # reuses ONE name exactly like a training loop reuses its
    # gradient names.
    steps = 8
    acc = np.zeros(big_n, np.float64)
    for i in range(steps):
        o = hvd.allreduce(jnp.asarray(base * (r + 1)), op=hvd.Sum,
                          name="c_ar_ef")
        acc += np.asarray(o, dtype=np.float64)
    mean_err = float(np.max(np.abs(acc / steps - expected)))
    assert mean_err < tol / 2, (
        "error feedback did not cancel quantization error across "
        "steps: mean err %g vs single-step bound %g" % (mean_err, tol))

    # -- broadcast (data movement: plain quantize/dequantize) ----------
    src = np.linspace(-2.0, 2.0, big_n).astype(np.float32)
    hb = hvd.broadcast(jnp.asarray(src) if r == 1
                       else jnp.zeros((big_n,), jnp.float32),
                       root_rank=1, name="c_bc")
    np.testing.assert_allclose(np.asarray(hb), src,
                               atol=2.0 / 254.0 * 1.05 + 1e-6)

    # -- allgather (ragged; per-member scales) -------------------------
    ag_rows = 8192 + r
    mine = (np.linspace(-1.0, 1.0, ag_rows * 4)
            .reshape(ag_rows, 4).astype(np.float32) * (r + 1))
    hg = hvd.allgather(jnp.asarray(mine), name="c_ag")
    exp = np.concatenate(
        [np.linspace(-1.0, 1.0, (8192 + j) * 4)
         .reshape(8192 + j, 4).astype(np.float32) * (j + 1)
         for j in range(n)])
    np.testing.assert_allclose(np.asarray(hg), exp,
                               atol=float(n) / 254.0 * 1.05 + 1e-6)

    # -- alltoall (per-sender scales ride along) -----------------------
    a2a_rows = 4096
    payload = (np.repeat(np.linspace(-1.0, 1.0, n), a2a_rows)[:, None]
               .astype(np.float32) + 0.5 * r)
    ha, hrecv = hvd.alltoall(
        jnp.asarray(np.tile(payload, (1, 4))),
        splits=[a2a_rows] * n, name="c_a2a")
    assert list(hrecv) == [a2a_rows] * n, hrecv
    exp_col = np.repeat(
        np.linspace(-1.0, 1.0, n)[r] + 0.5 * np.arange(n), a2a_rows)
    amax = 1.0 + 0.5 * (n - 1)
    np.testing.assert_allclose(np.asarray(ha)[:, 0],
                               exp_col.astype(np.float32),
                               atol=amax / 127.0 * 1.05 + 1e-6)

    # -- reducescatter (reduce leg compressed, local reassembly full) --
    rs_d0 = n * 4096
    rs_base = np.tile(np.linspace(-1.0, 1.0, rs_d0)[:, None],
                      (1, 4)).astype(np.float32)
    hr = hvd.reducescatter(jnp.asarray(rs_base * (r + 1)), op=hvd.Sum,
                           name="c_rs")
    np.testing.assert_allclose(
        np.asarray(hr),
        rs_base[r * 4096:(r + 1) * 4096] * sum(j + 1 for j in range(n)),
        atol=tol)

    # -- sub-threshold payloads stay flat, uncompressed and EXACT ------
    flat_before = _series_sum("mh_bus_bytes_total", op="allreduce",
                              path="flat")
    small = hvd.allreduce(np.full((64,), float(r + 1), np.float32),
                          op=hvd.Sum, name="c_small")
    np.testing.assert_array_equal(np.asarray(small),
                                  sum(j + 1.0 for j in range(n)))
    small_delta = _series_sum("mh_bus_bytes_total", op="allreduce",
                              path="flat") - flat_before
    assert small_delta == 64 * 4, small_delta  # payload bytes, no codec

    # -- residency: the quantize seam never bounces device payloads ---
    # (the numpy-typed inputs above legitimately host-stage once each;
    # a pure device payload must not move host_stages at all)
    stages = mc.host_stages
    dres = hvd.allreduce(jnp.ones((big_n,), jnp.float32), op=hvd.Sum,
                         name="c_dev")
    assert isinstance(dres, jax.Array)
    assert mc.host_stages == stages, (
        "device payload transited the host through the quantize seam")

    # -- the compiled wire is REALLY int8 ------------------------------
    if os.environ.get("HVD_TPU_DUMP_HLO"):
        for fam in ("hier_allreduce", "hier_broadcast",
                    "hier_allgather", "hier_alltoall",
                    "hier_reducescatter"):
            txts = [v for kk, v in mc.hlo.items()
                    if kk[0] == fam and kk[-1] == "int8"]
            assert txts, "no int8-codec %s program compiled" % fam
            htxt = "\n".join(txts)
            assert "xi8>" in htxt or "s8[" in htxt, (
                "%s: no int8 wire tensor in the compiled program "
                "(StableHLO xi8> / HLO s8[)" % fam)
            assert "all_gather" in htxt, (
                "%s: no local reassembly leg" % fam)

    print("MH_COMPRESSION_OK", r, flush=True)
    hvd.shutdown()
    os._exit(0)


if __name__ == "__main__":
    main()
