"""Multihost data-parallel training-step worker: N real processes over
one global mesh run ``make_data_parallel_step``; the resulting update is
verified numerically against a single-process full-batch reference —
gradients must be the exact global-batch mean."""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax


def loss_fn(params, batch):
    pred = jnp.tanh(batch["x"] @ params["w"]) + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def main():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    n_local = int(os.environ.get("TEST_LOCAL_DEVICES", "2"))
    per_proc = 2 * n_local  # 2 rows per device

    rng = np.random.RandomState(0)  # same seed everywhere
    gx = rng.randn(n * per_proc, 4).astype(np.float32)
    gy = rng.randn(n * per_proc, 3).astype(np.float32)
    params0 = {"w": rng.randn(4, 3).astype(np.float32),
               "b": rng.randn(3).astype(np.float32)}
    lr = 0.1

    step, init = hvd_jax.make_data_parallel_step(
        loss_fn, optax.sgd(lr), donate=False)
    params = hvd_jax.replicate(params0)
    opt_state = hvd_jax.replicate(init(params0))
    # Reference semantics: each process feeds ITS shard of the batch.
    batch = hvd_jax.shard_batch(
        {"x": gx[r * per_proc:(r + 1) * per_proc],
         "y": gy[r * per_proc:(r + 1) * per_proc]})

    params, opt_state, loss = step(params, opt_state, batch)
    got = hvd_jax.fetch(params)

    # Single-process full-batch reference (pure jax, no framework).
    ref_grads = jax.grad(loss_fn)(params0, {"x": gx, "y": gy})
    want = {k: params0[k] - lr * np.asarray(ref_grads[k])
            for k in params0}
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-5,
                                   atol=2e-6)
    ref_loss = float(loss_fn(params0, {"x": gx, "y": gy}))
    np.testing.assert_allclose(float(np.asarray(hvd_jax.fetch(loss))),
                               ref_loss, rtol=1e-5)
    print("MH_DP_OK", r, flush=True)
    hvd.shutdown()
    # Skip the jax gloo runtime's own atexit teardown, which can
    # SIGABRT on a 1-core box after all work completed (see
    # multihost_worker.py).
    os._exit(0)


if __name__ == "__main__":
    main()
