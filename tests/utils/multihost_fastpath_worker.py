"""Worker for the steady-state fast-path e2e tests (ISSUE 19): the
multihost engine freezes a negotiated schedule after
HOROVOD_FAST_PATH_WARM_CYCLES identical cycles (rank 0's verdict
adopted through the rendezvous KV), dispatches from the cache, and —
the part a unit test cannot certify — every loud-invalidation source
thaws it back to full negotiation with CORRECT values and NO hang on
every rank.  All scenarios need a rendezvous KV (the spawning test
runs a RendezvousServer in-process): a KV-less multi-member world
never freezes by design.

``TEST_SCENARIO=fp_shape`` — warm, freeze, then submit a tensor whose
shape does not match the frozen slot: the stage path thaws loudly
(reason=shape), the mismatching tensor renegotiates to the right
value, and the engine re-freezes on the new shape.

``TEST_SCENARIO=fp_membership`` — the elastic-resize-shaped membership
change: warm and freeze, then ``hvd.remove_process_set`` actuates the
same engine invalidation a resize does — the frozen schedule thaws
(reason=membership) before the engine touches its pending map.

``TEST_SCENARIO=fp_stale`` — injection-certified stale dispatch: the
spawning test arms ``engine.fastpath.stale_dispatch:drop@times=1``;
the first frozen bucket dispatch hits the site, thaws
(reason=staleness), and the staged tensor is flushed back through
full negotiation — correct value, no hang, then re-freezes once the
site is disarmed.

``TEST_SCENARIO=fp_route`` — the r21 degraded-route verdict: an
unbounded leg drop degrades every hier group to the flat plane while
the schedule freezes anyway (routing is orthogonal to the negotiated
profile); the SPMD ``check_degraded_routes`` demote verdict thaws
(reason=route) on every member BEFORE the plan invalidate, and the
next dispatch renegotiates onto the demoted flat route.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import faultline, metrics, resilience
from horovod_tpu.ops import fastpath

WARM = int(os.environ.get("HOROVOD_FAST_PATH_WARM_CYCLES", "3"))
N = 4096            # 16 KiB f32: below the hier threshold, fast cycles
BIG_N = 32768       # 128 KiB: past the hier threshold (fp_route)
CLS = str(BIG_N * 4)


def _plane():
    return fastpath.describe()["planes"]["multihost"]


def _thaws(reason):
    return metrics.series_sum("fastpath_thaws_total", reason=reason)


def _frozen_total():
    return metrics.series_sum("fastpath_frozen_cycles_total")


def _cycles_total():
    return metrics.series_sum("engine_cycles_total")


def _ar(r, n, name, elems=N):
    out = hvd.allreduce(np.full((elems,), float(r + 1), np.float32),
                        op=hvd.Sum, name=name)
    np.testing.assert_allclose(np.asarray(out),
                               float(sum(range(1, n + 1))))


def _warm_freeze(r, n, tag, elems=N):
    """Run the warm streak; the freeze verdict lands (rank 0 through
    the KV) before the tripping record executes, so the engine is
    frozen the moment the last warm allreduce returns."""
    for i in range(WARM):
        _ar(r, n, "%s.%d" % (tag, i), elems)
    assert _plane()["frozen"] is True, _plane()


def run_fp_shape():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    _warm_freeze(r, n, "warm")

    # Steady state: frozen dispatches move the frozen counter, never
    # the negotiation-cycle counter (satellite f: no double counting).
    cyc0, fr0 = _cycles_total(), _frozen_total()
    _ar(r, n, "steady.0")
    _ar(r, n, "steady.1")
    assert _frozen_total() - fr0 == 2, (fr0, _frozen_total())
    assert _cycles_total() == cyc0, (cyc0, _cycles_total())

    # A shape change thaws loudly and still reduces correctly.
    th0 = _thaws("shape")
    _ar(r, n, "shape.change", elems=2 * N)
    assert _thaws("shape") == th0 + 1, _thaws("shape")
    assert _plane()["frozen"] is False, _plane()

    # The engine re-freezes on the NEW shape (warm streak restarted
    # by the mismatching cycle itself, so WARM more trips it).
    _warm_freeze(r, n, "rewarm", elems=2 * N)
    hvd.shutdown()
    print("FASTPATH_OK %d" % r, flush=True)


def run_fp_membership():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    ps = hvd.add_process_set([0])  # registered SPMD on every rank
    _warm_freeze(r, n, "warm")

    # The resize-shaped membership actuation: removing a process set
    # invalidates it on the engine, which must thaw FIRST.
    th0 = _thaws("membership")
    assert hvd.remove_process_set(ps)
    assert _thaws("membership") == th0 + 1, _thaws("membership")
    assert _plane()["frozen"] is False, _plane()

    # The world keeps reducing correctly and re-freezes.
    _warm_freeze(r, n, "rewarm")
    _ar(r, n, "steady.post")
    hvd.shutdown()
    print("FASTPATH_OK %d" % r, flush=True)


def run_fp_stale():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    _warm_freeze(r, n, "warm")

    # The armed drop@times=1 fires at the first frozen bucket end:
    # thaw(staleness) + flush back through negotiation — the caller's
    # handle still resolves to the correct sum (no hang).
    th0 = _thaws("staleness")
    _ar(r, n, "stale.inject")
    assert _thaws("staleness") == th0 + 1, _thaws("staleness")
    assert _plane()["frozen"] is False, _plane()

    # Disarm at the same point on every rank; the engine re-warms.
    del os.environ["HVD_TPU_FAULT"]
    faultline.reset()
    _warm_freeze(r, n, "rewarm")
    _ar(r, n, "steady.post")
    hvd.shutdown()
    print("FASTPATH_OK %d" % r, flush=True)


def run_fp_route():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()

    # Hier-eligible payloads under an unbounded leg drop: every group
    # degrades to the flat plane (values stay correct) while the
    # negotiated profile — and therefore the freeze — is unaffected.
    _warm_freeze(r, n, "warm", elems=BIG_N)

    # The SPMD demote verdict (rank 0 streak >= threshold, adopted
    # through the KV) must thaw the frozen schedule on EVERY member.
    th0 = _thaws("route")
    verdict = resilience.check_degraded_routes(timeout=60.0)
    assert verdict is not None and verdict["action"] == "demote", verdict
    assert (verdict["op"], verdict["size_class"]) == ("allreduce", CLS), \
        verdict
    assert _thaws("route") == th0 + 1, _thaws("route")
    assert _plane()["frozen"] is False, _plane()

    # Post-thaw dispatches renegotiate onto the demoted flat route
    # with the fault still armed — correct values, no hier attempt.
    _ar(r, n, "steady.post", elems=BIG_N)
    hvd.shutdown()
    print("FASTPATH_OK %d" % r, flush=True)


def main():
    scenario = os.environ.get("TEST_SCENARIO", "fp_shape")
    run = {"fp_shape": run_fp_shape,
           "fp_membership": run_fp_membership,
           "fp_stale": run_fp_stale,
           "fp_route": run_fp_route}[scenario]
    run()


if __name__ == "__main__":
    main()
