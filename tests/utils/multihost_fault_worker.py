"""Fail-fast worker for the enqueue-ordering injection tests: one
allreduce whose result is either verified CORRECT (exit 0, marker
FAULT_OK) or failed LOUDLY with HorovodInternalError (exit 3, marker
FAULT_LOUD).  Any other outcome — a silently wrong reduction above all
— is a plain failure (assertion, rc 1).

The spawning test arms HVD_TPU_FAULT (e.g. core.enqueue.legacy_order,
the pre-fix enqueue ordering) and asserts the world never completes
with a corrupted value: loud errors are the acceptable failure mode,
wrong numbers never are.

``TEST_SCENARIO=delay_skew`` runs the delayed-but-alive leg instead:
a burst of verified allreduces under an armed ``delay`` action at a
multihost dispatch seam, followed by a ``SKEW_TOTALS <rank> <sum>
<count>`` report of this rank's ``mh_collective_seconds`` totals —
the spawning test asserts the delayed rank completed every group
(values correct, no error path) AND that the delay is visible as
latency skew (the PROMPT rank's window inflates by the wait; the
delayed rank's own dispatch→completion stays the fleet minimum — the
arrival-lag inversion common/skew.py scores)."""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.ops.engine import HorovodInternalError


def run_delay_skew():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    expected = float(sum(range(1, n + 1)))
    for i in range(12):
        out = hvd.allreduce(np.full((64,), float(r + 1), np.float32),
                            op=hvd.Sum, name="skew%d" % i)
        np.testing.assert_allclose(np.asarray(out), expected)
    from horovod_tpu.common import skew
    from horovod_tpu.common.metrics import snapshot
    total, count = skew._hist_totals(snapshot(),
                                     "mh_collective_seconds")
    print("SKEW_TOTALS %d %.6f %d" % (r, total, int(count)),
          flush=True)
    hvd.shutdown()
    print("FAULT_OK %d" % r, flush=True)


def main():
    if os.environ.get("TEST_SCENARIO") == "delay_skew":
        run_delay_skew()
        return
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    try:
        out = hvd.allreduce(np.full((8,), float(r + 1), np.float32),
                            op=hvd.Sum, name="inj")
    except HorovodInternalError as exc:
        print("FAULT_LOUD %d: %s" % (r, exc), flush=True)
        # Loud failure is a legitimate outcome under injection; the
        # world is poisoned, so skip hvd.shutdown()'s collective
        # teardown and exit with the designated code.
        os._exit(3)
    expected = float(sum(range(1, n + 1)))
    np.testing.assert_allclose(np.asarray(out), expected)
    hvd.shutdown()
    print("FAULT_OK %d" % r, flush=True)


if __name__ == "__main__":
    main()
