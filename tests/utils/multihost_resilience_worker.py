"""Worker for the self-healing data-plane e2e tests (ISSUE 18): the
hier cross-host legs run under the resilience guard, and this worker
certifies the two live behaviours a unit test cannot:

``TEST_SCENARIO=leg_flake`` — the spawning test arms a BOUNDED drop
(``mh.leg.drop:drop@times=2@rank=1``): rank 1's first hier dispatch
eats two injected transport faults, retries them under the backoff
budget, and the group still completes with the CORRECT value on every
rank.  Evidence asserted in-process: the victim's retry counter grew
by exactly the injected count, nobody recorded a collective failure,
and no route was demoted — a bounded flake costs latency, never the
job and never the topology.

``TEST_SCENARIO=leg_demote`` — an UNBOUNDED drop on every rank with a
demote threshold of 2: two consecutive retry exhaustions degrade each
group to the flat plane (values stay correct), the SPMD
``check_degraded_routes`` call demotes the (op, size_class) through
rank 0's KV verdict on ALL ranks, a demoted dispatch routes flat with
zero new retries, and after the fault is disarmed the re-probe window
(HOROVOD_LEG_REPROBE_SECS=1) re-promotes the class — the final
dispatch runs hier again.  Needs a rendezvous KV: the spawning test
runs a RendezvousServer in-process and passes
HOROVOD_RENDEZVOUS_ADDR/HOROVOD_SECRET_KEY.
"""

import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import faultline, metrics, resilience

# 32768 f32 = 128 KiB: past the 64 KiB hier threshold, so every
# dispatch engages the proc x local plane (and the resilience guard).
BIG_N = 32768
CLS = str(BIG_N * 4)  # pow2 class of the payload bytes (already a pow2)


def _path_counts():
    """{path: total} from mh_collective_path_total for allreduce."""
    fam = metrics.snapshot().get("mh_collective_path_total") or {}
    out = {}
    for row in fam.get("series", []):
        labels = row.get("labels", {})
        if labels.get("op") != "allreduce":
            continue
        path = labels.get("path", "?")
        out[path] = out.get(path, 0.0) + float(row.get("value", 0.0))
    return out


def _verified_allreduce(r, n, name):
    out = hvd.allreduce(np.full((BIG_N,), float(r + 1), np.float32),
                        op=hvd.Sum, name=name)
    np.testing.assert_allclose(np.asarray(out),
                               float(sum(range(1, n + 1))))


def run_leg_flake():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    for i in range(4):
        _verified_allreduce(r, n, "flake%d" % i)
    desc = resilience.describe()
    if r == 1:
        # The victim absorbed exactly the two injected faults.
        assert desc["leg_retries_total"] == 2.0, desc
    else:
        assert desc["leg_retries_total"] == 0.0, desc
    # Absorbed flakes are not failures and never demote a route.
    assert desc["failures_by_reason"] == {}, desc
    assert desc["demoted_routes"] == [], desc
    # Every group rode the hier plane (the retries happened IN it).
    assert _path_counts().get("hier", 0) >= 4, _path_counts()
    hvd.shutdown()
    print("RESILIENCE_OK %d" % r, flush=True)


def run_leg_demote():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()

    # Phase 1: the armed unbounded drop exhausts the retry budget on
    # every group; each degrades to the flat plane with correct values.
    _verified_allreduce(r, n, "demote0")
    _verified_allreduce(r, n, "demote1")
    desc = resilience.describe()
    assert desc["demoted_routes"] == [], desc  # no rank-local demotion
    counts = _path_counts()
    assert counts.get("flat", 0) >= 2, counts  # degraded fallbacks ran

    # Phase 2: the SPMD check — rank 0's streak (2 >= threshold 2)
    # becomes a KV verdict every member adopts at the same index.
    verdict = resilience.check_degraded_routes(timeout=60.0)
    assert verdict is not None and verdict["action"] == "demote", verdict
    assert (verdict["op"], verdict["size_class"]) == ("allreduce", CLS), \
        verdict
    assert resilience.demoted("allreduce", CLS)
    assert resilience.describe()["demoted_routes"] == [
        {"op": "allreduce", "size_class": CLS}]

    # Phase 3: a demoted dispatch routes flat at the gate — no hier
    # attempt, so no new retries even with the fault still armed.
    retries_before = resilience.describe()["leg_retries_total"]
    hier_before = _path_counts().get("hier", 0)
    _verified_allreduce(r, n, "demoted_flat")
    assert resilience.describe()["leg_retries_total"] == retries_before
    assert _path_counts().get("hier", 0) == hier_before

    # Phase 4: heal the leg (every rank disarms at the same point),
    # wait out the re-probe window, and check again: rank 0's probe
    # clock re-promotes the class through the same KV protocol.
    del os.environ["HVD_TPU_FAULT"]
    faultline.reset()
    time.sleep(1.2)  # > HOROVOD_LEG_REPROBE_SECS=1
    verdict = resilience.check_degraded_routes(timeout=60.0)
    assert verdict is not None and verdict["action"] == "promote", verdict
    assert not resilience.demoted("allreduce", CLS)

    # Phase 5: the re-promoted class rides hier again, healthily.
    _verified_allreduce(r, n, "promoted")
    assert _path_counts().get("hier", 0) == hier_before + 1, \
        _path_counts()
    hvd.shutdown()
    print("RESILIENCE_OK %d" % r, flush=True)


def main():
    scenario = os.environ.get("TEST_SCENARIO", "leg_flake")
    if scenario == "leg_demote":
        run_leg_demote()
    else:
        run_leg_flake()


if __name__ == "__main__":
    main()
