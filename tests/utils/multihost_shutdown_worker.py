"""Shutdown-ordering worker: hvd.init -> collective -> hvd.shutdown,
with per-rank exit skew so the spawning test exercises BOTH exit
orderings (rank 0 gone first while peers still tear down, and rank 0
last).  The synchronized-teardown barrier in shutdown_jax_distributed
must make every ordering exit rc=0 on every rank — pre-fix, the first
process exit could FATAL survivors inside jax.distributed.shutdown().

Env: TEST_EXIT_DELAY_RANK<r> seconds between hvd.shutdown returning
and process exit (one rank's process lingers); teardown-ARRIVAL skew
is injected via the hvd.shutdown.pre_barrier faultline site instead."""

import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.full((4,), float(r + 1), np.float32),
                        op=hvd.Sum, name="sd")
    expected = float(sum(range(1, n + 1)))
    np.testing.assert_allclose(np.asarray(out), expected)
    hvd.shutdown()
    time.sleep(float(os.environ.get("TEST_EXIT_DELAY_RANK%d" % r, "0")))
    print("MH_SHUTDOWN_OK %d" % r, flush=True)


if __name__ == "__main__":
    main()
