"""Fault-injection worker for the execution-phase watchdog: rank 1
negotiates the marked group but NEVER DISPATCHES its side of the
compiled global program, while staying alive — so rank 0 wedges inside
the runtime on a collective its peer never joins.  This is the
deadlock class the negotiation-phase stall inspector cannot see, and
(unlike a process death, which CPU gloo detects with a connection
error) the transport cannot detect it either — exactly the ICI
behavior on a pod, where a stuck or dying member leaves survivors
blocked with no signal.  The device-plane watchdog
(HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS) must fail rank 0's handle with a
diagnostic naming the group, and the engine must reject new work."""

import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n

    from horovod_tpu.common import basics
    eng = basics._get_mh_engine()

    if r == 1:
        orig = eng._execute

        def never_dispatch_the_wedged_group(g):
            if any(e["name"] == "wedge" for e in g["entries"]):
                return  # negotiated, never dispatched; stay alive
            orig(g)

        eng._execute = never_dispatch_the_wedged_group

    # A clean collective first: both planes warm, world healthy.
    out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="ok")
    np.testing.assert_allclose(np.asarray(out), float(n))

    h = hvd.allreduce_async(np.full((8,), float(r + 1), np.float32),
                            op=hvd.Sum, name="wedge")
    if r == 1:
        # Stay alive (heartbeats flowing, transport healthy) long
        # enough for rank 0's watchdog to fire and rank 0 to finish.
        # Once rank 0 hard-exits, the jax coordination service may
        # kill this process first — the exit code is runtime noise;
        # the test only requires that the wedge marker never prints.
        time.sleep(25)
        os._exit(17)

    try:
        h.wait(60)
    except Exception as exc:
        msg = str(exc)
        assert "watchdog" in msg and "wedge" in msg, (
            "expected the watchdog diagnostic naming the group, "
            "got: %r" % msg)
        # The engine is poisoned: new work must fail fast, not park
        # behind the wedged device program.
        try:
            hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                          name="after_watchdog")
        except Exception:
            pass
        else:
            raise AssertionError(
                "engine accepted new work after the watchdog fired")
        print("MH_WATCHDOG_OK", r, flush=True)
        # The runtime thread is wedged in the dead collective by
        # design; hard-exit past it.
        os._exit(0)
    raise AssertionError(
        "the wedged collective completed although rank 1 died before "
        "dispatch")


if __name__ == "__main__":
    main()
