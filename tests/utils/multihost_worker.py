"""Worker for multihost-mode tests: N real processes, each with forced
CPU devices, joined into ONE global JAX runtime — the control plane rides
the native core, payloads execute as XLA collectives over the global
mesh (gloo carries the cross-process legs on the CPU test world)."""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "4")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd


def main():
    tl_base = os.environ.get("TEST_TIMELINE_BASE")
    if tl_base:
        os.environ["HOROVOD_TIMELINE"] = "%s.%s.json" % (
            tl_base, os.environ.get("HOROVOD_RANK", "0"))
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    n_local = int(os.environ.get("TEST_LOCAL_DEVICES", "4"))
    assert jax.process_count() == n, jax.process_count()
    assert len(jax.devices()) == n * n_local, len(jax.devices())
    assert len(jax.local_devices()) == n_local

    # allreduce: average with prescale, sum, min/max/product, fusion.
    out = hvd.allreduce(np.full((5,), float(r + 1), np.float32),
                        op=hvd.Average, name="avg", prescale_factor=2.0)
    np.testing.assert_allclose(
        np.asarray(out), 2.0 * np.mean([i + 1.0 for i in range(n)]))

    hs = [hvd.allreduce_async(
        np.full((3,), float(r) * (i + 1), np.float32),
        op=hvd.Sum, name="fuse.%d" % i) for i in range(4)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(
            np.asarray(h.wait(30)),
            (i + 1.0) * sum(range(n)))

    x = np.array([r + 1], dtype=np.int32)
    assert int(np.asarray(hvd.allreduce(x, op=hvd.Min, name="mn"))[0]) == 1
    assert int(np.asarray(hvd.allreduce(x, op=hvd.Max, name="mx"))[0]) == n
    prod = hvd.allreduce(np.array([2.0], np.float32), op=hvd.Product,
                         name="pd")
    np.testing.assert_allclose(np.asarray(prod), [2.0 ** n])

    # grouped allreduce: negotiated atomically, fused on the device.
    outs = hvd.grouped_allreduce(
        [np.full((2,), float(r), np.float32),
         np.full((7,), float(r + 1), np.float32)], op=hvd.Sum,
        name="grp")
    np.testing.assert_allclose(np.asarray(outs[0]), sum(range(n)))
    np.testing.assert_allclose(np.asarray(outs[1]),
                               sum(i + 1 for i in range(n)))

    # grouped allgather / reducescatter (v0.28 variants) negotiate
    # atomically on the device plane too.
    g0, g1 = hvd.grouped_allgather(
        [np.full((r + 1, 2), float(r), np.float32),
         np.full((3,), float(r), np.float32)], name="gag")
    assert np.asarray(g0).shape == (n * (n + 1) // 2, 2)
    assert np.asarray(g1).shape == (3 * n,)
    r0, r1 = hvd.grouped_reducescatter(
        [np.arange(n * 2, dtype=np.float32),
         np.ones(n, np.float32) * (r + 1)], op=hvd.Sum, name="grs")
    np.testing.assert_allclose(
        np.asarray(r0),
        np.arange(n * 2, dtype=np.float32)[r * 2:(r + 1) * 2] * n)
    np.testing.assert_allclose(np.asarray(r1),
                               sum(range(1, n + 1)))

    # broadcast from root 1.
    x = (np.arange(6, dtype=np.float32).reshape(2, 3) if r == 1
         else np.zeros((2, 3), np.float32))
    out = hvd.broadcast(x, root_rank=1, name="bc")
    np.testing.assert_allclose(
        np.asarray(out), np.arange(6, dtype=np.float32).reshape(2, 3))

    # allgather, ragged: rank r contributes r+1 rows.
    x = np.full((r + 1, 2), float(r), np.float32)
    out = np.asarray(hvd.allgather(x, name="ag"))
    expected = np.concatenate(
        [np.full((j + 1, 2), float(j), np.float32) for j in range(n)])
    np.testing.assert_allclose(out, expected)

    # alltoall with ragged splits: rank r sends (j+1) rows to rank j.
    splits = [j + 1 for j in range(n)]
    x = np.full((sum(splits), 2), float(r), np.float32)
    out, recv_splits = hvd.alltoall(x, splits=splits, name="a2a")
    assert list(recv_splits) == [r + 1] * n, recv_splits
    out = np.asarray(out)
    assert out.shape == ((r + 1) * n, 2)
    np.testing.assert_allclose(
        out[:, 0], np.repeat(np.arange(n, dtype=np.float32), r + 1))

    # reducescatter, uneven rows (n*2+1): reference chunk math.
    d0 = n * 2 + 1
    x = np.tile(np.arange(d0, dtype=np.float32)[:, None], (1, 3))
    out = np.asarray(hvd.reducescatter(x, op=hvd.Sum, name="rs"))
    base, rem = divmod(d0, n)
    my_rows = base + (1 if r < rem else 0)
    start = r * base + min(r, rem)
    assert out.shape == (my_rows, 3), out.shape
    np.testing.assert_allclose(
        out, n * np.tile(np.arange(start, start + my_rows,
                                   dtype=np.float32)[:, None], (1, 3)))

    # Device-residency contract: jax.Array payloads never transit
    # numpy (no host staging), results come back as device arrays, and
    # the emitted programs are real collective HLO (dump enabled via
    # HVD_TPU_DUMP_HLO in the spawner).
    import jax.numpy as jnp
    from horovod_tpu.common import basics
    mc = basics._get_mh_engine().collectives_for(0)
    before = mc.host_stages
    dx = jnp.full((8,), float(r + 1), jnp.float32)
    dout = hvd.allreduce(dx, op=hvd.Sum, name="dev_ar")
    assert isinstance(dout, jax.Array), type(dout)
    np.testing.assert_allclose(np.asarray(dout),
                               sum(i + 1.0 for i in range(n)))
    d2, _ = hvd.alltoall(jnp.arange(n * 2, dtype=jnp.float32
                                    ).reshape(n * 2, 1),
                         splits=[2] * n, name="dev_a2a")
    assert isinstance(d2, jax.Array), type(d2)
    np.testing.assert_allclose(
        np.asarray(d2)[:, 0], np.concatenate(
            [[2 * r, 2 * r + 1] for _ in range(n)]))
    d3 = hvd.reducescatter(jnp.ones((n * 3, 2), jnp.float32),
                           op=hvd.Sum, name="dev_rs")
    assert isinstance(d3, jax.Array), type(d3)
    np.testing.assert_allclose(np.asarray(d3), float(n))
    # Min reducescatter rides the bytes-proportional all_to_all path
    # (r4).  POSITION-dependent payload: chunk j's expected values are
    # distinct, so delivering the wrong rank's chunk (a split/concat
    # axis or mesh-ordering regression in alltoall_chunk_reduce) fails
    # the numeric check, not just the structural HLO one below.
    base = np.tile(np.arange(n * 2, dtype=np.float32)[:, None], (1, 2))
    d3m = hvd.reducescatter(
        jnp.asarray(base + 10.0 * r), op=hvd.Min, name="dev_rs_min")
    assert isinstance(d3m, jax.Array), type(d3m)
    np.testing.assert_allclose(  # min over ranks = base; my chunk rows
        np.asarray(d3m), base[r * 2:(r + 1) * 2])
    # Device-plane Adasum (r4): the ppermute XOR-tree combine runs on
    # the mesh — device payloads stay resident, results match the host
    # recursive-halving oracle.  Non-pow2 worlds must error loudly.
    my_vec = (np.arange(6, dtype=np.float32) + 1.0) * (r + 1)
    if n & (n - 1) == 0:
        d4 = hvd.allreduce(jnp.asarray(my_vec), op=hvd.Adasum,
                           name="dev_adasum")
        assert isinstance(d4, jax.Array), type(d4)
        from horovod_tpu.utils.adasum import adasum_reduce_stacked
        oracle = adasum_reduce_stacked(np.stack(
            [(np.arange(6, dtype=np.float32) + 1.0) * (j + 1)
             for j in range(n)]))
        np.testing.assert_allclose(np.asarray(d4), np.asarray(oracle),
                                   rtol=1e-5)
    else:
        try:
            hvd.allreduce(jnp.asarray(my_vec), op=hvd.Adasum,
                          name="dev_adasum_bad")
        except Exception as exc:
            assert "power-of-two" in str(exc), (
                "expected the pow2 Adasum rejection, got: %r" % exc)
        else:
            raise AssertionError(
                "Adasum on a non-power-of-two world must error")
    # Multi-chip eager plane (r5): payloads >= the hierarchical
    # threshold shard across EVERY local chip — cross-host reduce
    # moves 1/k of the bytes per chip, a local all_gather reassembles.
    # Device-resident (no host staging), numerically exact, and the
    # compiled program must SPAN all n*n_local devices with real
    # reduce/gather HLO (not replication through device 0).
    big_n = 32768  # 128 KiB f32 >= the 64 KiB default threshold
    bout = hvd.allreduce(jnp.full((big_n,), float(r + 1), jnp.float32),
                         op=hvd.Sum, name="hier_ar")
    assert isinstance(bout, jax.Array), type(bout)
    np.testing.assert_allclose(np.asarray(bout),
                               sum(i + 1.0 for i in range(n)))
    # A burst of large entries: whatever composition fuses rides the
    # packed bucket, and the bucket (>= threshold) rides the
    # hierarchical plane too.
    bhs2 = [hvd.allreduce_async(
        jnp.full((16384,), float(r + 1) * (i + 1), jnp.float32),
        op=hvd.Sum, name="hier_burst.%d" % i) for i in range(3)]
    tot = sum(j + 1.0 for j in range(n))
    for i, h in enumerate(bhs2):
        np.testing.assert_allclose(np.asarray(h.wait(60)),
                                   np.full((16384,), tot * (i + 1)))
    # Multi-chip legs for the OTHER four eager ops (r9): payloads at or
    # above the threshold shard across every local chip on all five
    # collectives.  Position-dependent payloads so a chunk delivered to
    # the wrong slot fails numerically, not just structurally.
    # TEST_HIER_OPS=0 skips these sections (the 3-proc world runs them
    # at ~3x the compile+gloo cost for no extra coverage — the 2-proc
    # x 4-local world already spans multi-proc x multi-local).
    hier_ops = os.environ.get("TEST_HIER_OPS", "1") == "1"
    if hier_ops:
        bc_n = 32768  # 128 KiB f32 >= the 64 KiB default threshold
        src = np.arange(bc_n, dtype=np.float32)
        hb = hvd.broadcast(jnp.asarray(src) if r == 1
                           else jnp.zeros((bc_n,), jnp.float32),
                           root_rank=1, name="hier_bc")
        assert isinstance(hb, jax.Array), type(hb)
        np.testing.assert_allclose(np.asarray(hb), src)

        ag_rows = 8192 + r  # ragged: rank r contributes 8192+r rows of 4
        mine = (np.arange(ag_rows * 4, dtype=np.float32).reshape(ag_rows, 4)
                + r * 1e6)
        hg = hvd.allgather(jnp.asarray(mine), name="hier_ag")
        assert isinstance(hg, jax.Array), type(hg)
        np.testing.assert_allclose(
            np.asarray(hg),
            np.concatenate([np.arange((8192 + j) * 4, dtype=np.float32)
                            .reshape(8192 + j, 4) + j * 1e6
                            for j in range(n)]))

        a2a_rows = 4096  # per-dest block 64 KiB
        payload = np.repeat(np.arange(n, dtype=np.float32),
                            a2a_rows)[:, None] + 100.0 * r
        ha, hrecv = hvd.alltoall(
            jnp.asarray(np.tile(payload, (1, 4))),
            splits=[a2a_rows] * n, name="hier_a2a")
        assert isinstance(ha, jax.Array), type(ha)
        assert list(hrecv) == [a2a_rows] * n, hrecv
        np.testing.assert_allclose(  # from source m: rows valued r + 100*m
            np.asarray(ha)[:, 0],
            np.repeat(100.0 * np.arange(n, dtype=np.float32) + r, a2a_rows))

        rs_d0 = n * 4096
        base = np.tile(np.arange(rs_d0, dtype=np.float32)[:, None], (1, 4))
        hr = hvd.reducescatter(jnp.asarray(base * (r + 1)), op=hvd.Sum,
                               name="hier_rs")
        assert isinstance(hr, jax.Array), type(hr)
        np.testing.assert_allclose(
            np.asarray(hr),
            base[r * 4096:(r + 1) * 4096] * sum(j + 1 for j in range(n)))

    if n_local > 1:
        assert mc.local_size == n_local, mc.local_size
        if os.environ.get("HVD_TPU_DUMP_HLO"):
            # Every hier program must SPAN all n*n_local partitions
            # with a real cross-host leg plus the local all_gather
            # reassembly leg.
            fams = [("hier_allreduce", "all_reduce")]
            if hier_ops:
                fams += [("hier_broadcast", "all_reduce"),
                         ("hier_allgather", "all_gather"),
                         ("hier_alltoall", "all_to_all"),
                         ("hier_reducescatter", "reduce_scatter")]
            for fam, leg in fams:
                txts = [v for kk, v in mc.hlo.items() if kk[0] == fam]
                assert txts, ("large %s did not ride the hier plane"
                              % fam)
                htxt = "\n".join(txts)
                assert "all_gather" in htxt, (
                    "%s: no local all_gather leg" % fam)
                assert leg in htxt, (
                    "%s: no cross-host %s leg" % (fam, leg))
                assert ("num_partitions = %d" % (n * n_local)) in htxt, (
                    "%s program does not span all %d devices"
                    % (fam, n * n_local))
    # Hier cache flatness (r9): a burst of varying shapes in ONE size
    # class per op must reuse ONE hier executable per op family — the
    # packed-bucket recompile-cliff treatment holds on the multi-chip
    # plane too.  (On single-local-chip worlds the hier families stay
    # empty and the assertion is vacuous.)
    def _op_keys(op):
        return sum(1 for kk in mc._fns.keys() if kk[0] == op)
    if hier_ops:
        hier_before = {op: _op_keys(op) for op in (
            "hier_allgather", "hier_alltoall", "hier_reducescatter",
            "hier_broadcast")}
        for i in range(3):
            rows_i = 8193 + 7 * i + r
            g = hvd.allgather(jnp.full((rows_i, 4), 1.0 + r, jnp.float32),
                              name="hag.%d" % i)
            assert np.asarray(g).shape == (
                sum(8193 + 7 * i + j for j in range(n)), 4)
            spl = [4097 + i] * n
            a2, rcv = hvd.alltoall(
                jnp.ones((sum(spl), 4), jnp.float32), splits=spl,
                name="ha2a.%d" % i)
            assert list(rcv) == [4097 + i] * n, rcv
            rs = hvd.reducescatter(
                jnp.ones((n * (4097 + i), 4), jnp.float32), op=hvd.Sum,
                name="hrs.%d" % i)
            np.testing.assert_allclose(np.asarray(rs), float(n))
            bc = hvd.broadcast(
                jnp.full((16385 + 3 * i,), float(r), jnp.float32),
                root_rank=0, name="hbc.%d" % i)
            np.testing.assert_allclose(np.asarray(bc), 0.0)
        for op, before_ct in hier_before.items():
            added = _op_keys(op) - before_ct
            assert added <= 1, (
                "hier %s burst grew the executable cache by %d keys "
                "(recompile cliff on the multi-chip plane)" % (op, added))

    assert mc.host_stages == before, (
        "device payloads transited the host: %d stagings"
        % (mc.host_stages - before))
    if os.environ.get("HVD_TPU_DUMP_HLO"):
        hlo = "\n".join(mc.hlo.values())
        assert "all_to_all" in hlo, "no all_to_all HLO emitted"
        assert "reduce_scatter" in hlo, "no reduce_scatter HLO emitted"
        assert "all_reduce" in hlo, "no all_reduce HLO emitted"
        if n & (n - 1) == 0:
            assert "collective_permute" in hlo, (
                "no collective_permute HLO from device Adasum")
        # Bytes-proportionality, structurally: Min reducescatter must
        # be one all_to_all with NO all_gather (1x payload bytes, not
        # the N x full-reduce-then-slice fallback); Product allreduce
        # must carry the all_to_all reduce-scatter stage.
        rs_min = "\n".join(v for k, v in mc.hlo.items()
                           if k[0] == "reducescatter" and "Min" in k)
        assert rs_min and "all_to_all" in rs_min, rs_min or "missing"
        assert "all_gather" not in rs_min, (
            "Min reducescatter still moves N x bytes:\n" + rs_min)
        prod = "\n".join(v for k, v in mc.hlo.items()
                         if k[0] == "fused_allreduce" and "Product" in k)
        assert prod and "all_to_all" in prod, prod or "missing"

    # Async burst (DistributedOptimizer traffic shape): many uniquely
    # named in-flight device-array ops of varying shapes.  Whatever
    # composition each negotiation cycle fuses rides the packed fusion
    # buffer (bucket-keyed executable — no per-composition recompile)
    # and the executor's pipeline window keeps groups overlapped.
    bhs = [hvd.allreduce_async(
        jnp.full((5 + i,), float(r + 1) * (i + 1), jnp.float32),
        op=hvd.Sum, name="burst.%d" % i) for i in range(12)]
    tot = sum(j + 1.0 for j in range(n))
    for i, h in enumerate(bhs):
        res = h.wait(60)
        assert isinstance(res, jax.Array), type(res)
        np.testing.assert_allclose(
            np.asarray(res), np.full((5 + i,), tot * (i + 1)))

    # Packed per-op programs (r5): a burst of VARYING compositions per
    # op must reuse ONE executable per size class — the allreduce
    # packed-bucket recompile-cliff treatment extended to allgather /
    # alltoall / reducescatter / broadcast.  Shapes below all land in
    # the same power-of-two bucket, so the cache may grow by at most
    # one key per op family.
    cache_before = {op: _op_keys(op) for op in (
        "allgather", "alltoall", "reducescatter", "broadcast")}
    for i in range(5):
        g = hvd.allgather(jnp.full((r + 1 + i, 2), float(r), jnp.float32),
                          name="cag.%d" % i)
        assert np.asarray(g).shape == (sum(j + 1 + i for j in range(n)),
                                       2)
        spl = [1 + (i + j + r) % 3 for j in range(n)]
        a2, rcv = hvd.alltoall(
            jnp.ones((sum(spl), 2), jnp.float32), splits=spl,
            name="ca2a.%d" % i)
        assert np.asarray(a2).shape == (sum(rcv), 2)
        rs = hvd.reducescatter(jnp.ones((n + i, 2), jnp.float32),
                               op=hvd.Sum, name="crs.%d" % i)
        np.testing.assert_allclose(np.asarray(rs), float(n))
        bc = hvd.broadcast(jnp.full((3 + 2 * i,), float(r), jnp.float32),
                           root_rank=0, name="cbc.%d" % i)
        np.testing.assert_allclose(np.asarray(bc), 0.0)
    for op, before_ct in cache_before.items():
        added = _op_keys(op) - before_ct
        assert added <= 1, (
            "packed %s burst grew the executable cache by %d keys "
            "(recompile cliff)" % (op, added))

    # barrier + process-set-scoped collective on even ranks.
    hvd.barrier()
    ps = hvd.add_process_set([i for i in range(0, n, 2)])
    if r % 2 == 0:
        out = hvd.allreduce(np.full((3,), float(r), np.float32),
                            op=hvd.Sum, name="ps_ar", process_set=ps)
        np.testing.assert_allclose(
            np.asarray(out), sum(float(i) for i in range(0, n, 2)))
    hvd.barrier()

    # join with uneven data: rank r runs r+1 steps then joins; device
    # allreduces keep flowing with joined ranks contributing zeros.
    for step in range(r + 1):
        hvd.allreduce_async(np.full((4,), 1.0, np.float32),
                            op=hvd.Sum, name="j.%d.%d" % (r, step))
    last = hvd.join()
    assert 0 <= last < n

    print("MULTIHOST_OK", r, flush=True)
    hvd.shutdown()
    if tl_base:
        # The executor records per-tensor device-exec spans (reference
        # timeline EXEC_* phases) — assert they landed in the trace.
        tl = open(os.environ["HOROVOD_TIMELINE"]).read()
        assert "EXEC_DEVICE_ALLREDUCE" in tl, "no device exec spans"
    # The jax gloo/distributed runtime can SIGABRT in its own atexit
    # teardown on a 1-core box ("FATAL: exception not rethrown") after
    # all work AND our shutdown completed; hard-exit past it so the
    # test judges the work, not third-party exit races.
    os._exit(0)


if __name__ == "__main__":
    main()
