"""mxnet trace-replay contract worker: installs a fake ``mxnet``
module implementing the recorded API surface (nd.NDArray / nd.array /
gluon.Trainer) BEFORE the adapter imports, then drives the
real-mxnet branches — NDArray reconstruction and DistributedTrainer
gradient averaging — over a REAL multi-process hvd world."""

import os
import sys
import types

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def _install_fake_mxnet():
    mx = types.ModuleType("mxnet")
    nd = types.ModuleType("mxnet.nd")
    gluon = types.ModuleType("mxnet.gluon")

    class NDArray:
        def __init__(self, arr, ctx="cpu(0)"):
            self._arr = np.array(arr)
            self.context = ctx

        def asnumpy(self):
            return self._arr.copy()

        @property
        def shape(self):
            return self._arr.shape

        @property
        def dtype(self):
            return self._arr.dtype

        def __setitem__(self, key, value):
            if isinstance(value, NDArray):
                value = value._arr
            self._arr[key] = np.asarray(value)

    def array(arr, ctx=None, dtype=None):
        a = np.asarray(arr, dtype=dtype)
        return NDArray(a, ctx=ctx or "cpu(0)")

    nd.NDArray = NDArray
    nd.array = array

    class Trainer:
        """The slice of gluon.Trainer the adapter subclasses: _params,
        _scale, and the (params, optimizer, optimizer_params) ctor."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     **kwargs):
            self._params = (list(params.values())
                            if hasattr(params, "values")
                            else list(params))
            self._scale = 1.0

        def step(self, batch_size):
            self._allreduce_grads()

        def _allreduce_grads(self):
            pass

    gluon.Trainer = Trainer
    mx.nd = nd
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
    sys.modules["mxnet.gluon"] = gluon
    return mx


class _Param:
    def __init__(self, grad):
        self.grad_req = "write"
        self._grad = grad

    def list_grad(self):
        return [self._grad]


def main():
    mx = _install_fake_mxnet()
    import horovod_tpu.mxnet as hvd
    assert hvd.mpi_ops._mx is mx, "adapter did not bind the fake mxnet"

    hvd.init()
    r, n = hvd.rank(), hvd.size()

    # Real-mxnet branch: NDArray in -> NDArray out via _mx.nd.array.
    x = mx.nd.array(np.full(4, float(r + 1), np.float32))
    out = hvd.allreduce(x, op=hvd.Sum, name="mx_ar")
    assert isinstance(out, mx.nd.NDArray), type(out)
    np.testing.assert_allclose(out.asnumpy(),
                               sum(i + 1.0 for i in range(n)))

    # DistributedTrainer: real gluon-Trainer subclass path; the
    # in-place grad allreduce must land the world sum (the Trainer's
    # _scale carries the 1/size).
    g = mx.nd.array(np.full(3, float(r + 1), np.float32))
    trainer = hvd.DistributedTrainer([_Param(g)], "sgd")
    assert abs(trainer._scale - 1.0 / n) < 1e-9
    trainer._allreduce_grads()
    np.testing.assert_allclose(g.asnumpy(),
                               sum(i + 1.0 for i in range(n)))

    print("MX_CONTRACT_OK", r, flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
