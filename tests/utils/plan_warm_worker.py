"""Worker for the plan-cache warm-start e2e (test_plancache.py + the
CI perf-smoke step): one rank of a 2-proc tcp world run TWICE against
a shared HOROVOD_PLAN_CACHE_DIR.

PLAN_PHASE=cold — empty cache: asserts the probe was a loud miss, then
drives enough steady allreduce traffic for the rank-0 native GP tuner
to converge; shutdown persists the plan blob.

PLAN_PHASE=warm — primed cache: asserts ``plan_cache_hits_total`` > 0
and ``plan_apply_total{source="cache"}`` > 0 right after ``init()``,
that the tuner's warm-up window was skipped BEFORE any traffic, and —
when the persisted plan was converged — that the rerun records ZERO new
GP samples (re-tuning skipped entirely).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import basics, metrics
from horovod_tpu.utils import plancache


def main():
    phase = os.environ["PLAN_PHASE"]
    steps = int(os.environ.get("PLAN_STEPS", "60"))
    # An explicit operator cycle-time env legitimately suppresses the
    # tuned-point warm start (env wins, the precedence rule this plane
    # inherits from r9), so this world must start with the cycle-time
    # keys UNSET — the spawner passes them via ``pop_env``, which also
    # keeps the harness's own fast-cycle pin off.
    assert "HOROVOD_CYCLE_TIME" not in os.environ
    assert "HVD_TPU_CYCLE_TIME" not in os.environ
    hvd.init()
    rank = hvd.rank()
    size = hvd.size()
    core = basics._state.tcp_core
    assert core is not None, "this worker needs a tcp world"
    st0 = core.autotune_state()

    if phase == "cold":
        assert metrics.series_sum("plan_cache_hits_total") == 0
        assert metrics.series_sum("plan_cache_misses_total") == 1
        assert metrics.series_sum("plan_apply_total", source="cache") == 0
        if rank == 0:
            assert st0["warmup_left"] > 0, st0  # cold tuner warms up
    else:
        assert phase == "warm", phase
        assert metrics.series_sum("plan_cache_hits_total") > 0
        assert metrics.series_sum(
            "plan_apply_total", source="cache") > 0
        if rank == 0:
            # The cached operating point was adopted with the warm-up
            # window skipped — before ANY traffic ran.
            assert st0["warmup_left"] == 0, st0

    # Steady allreduce traffic: the cold run samples its way to a
    # converged operating point, the warm run must already be there.
    x = np.full((4096,), float(rank), np.float32)
    out = None
    for it in range(steps):
        out = hvd.synchronize(
            hvd.allreduce_async(x, op=hvd.Sum, name="t.%d" % (it % 3)))
    np.testing.assert_allclose(np.asarray(out), float(sum(range(size))))

    st1 = core.autotune_state()
    if rank == 0:
        if phase == "cold":
            assert st1["samples"] > 0, st1
        elif st0["converged"]:
            # A converged plan freezes the tuner: the rerun skips
            # re-tuning entirely, not just the warm-up window.
            assert st1["samples"] == 0, st1
    hvd.shutdown()

    if phase == "cold" and rank == 0:
        # The blob must exist before the warm run starts.
        d = os.environ["HOROVOD_PLAN_CACHE_DIR"]
        blobs = [f for f in os.listdir(d) if f.endswith(".plan")]
        assert blobs, "cold run persisted no plan blob in %s" % d
    print("PLAN_%s_OK rank=%d" % (phase.upper(), rank))


if __name__ == "__main__":
    main()
