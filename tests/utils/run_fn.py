"""Top-level function for horovod_tpu.runner.run() pickling tests."""
import os


def rank_times_two():
    return int(os.environ["HOROVOD_RANK"]) * 2


def elastic_rank_value():
    """Real elastic world: init via the driver rendezvous, one
    allreduce across the wire, value encodes (rank, world size)."""
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    total = float(np.asarray(
        hvd.allreduce(np.ones(1, np.float32), op=hvd.SUM))[0])
    rank = hvd.rank()
    hvd.shutdown()
    return rank * 10 + int(total)
