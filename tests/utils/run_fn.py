"""Top-level function for horovod_tpu.runner.run() pickling tests."""
import os


def rank_times_two():
    return int(os.environ["HOROVOD_RANK"]) * 2
