"""Shared multi-process world spawner for adapter tests.

Real subprocess worlds over localhost TCP rendezvous — the reference's
``horovodrun -np N pytest`` strategy (SURVEY.md §4) without the
launcher wrapper.  Ports are probed for bindability before committing
to a base (earlier suite tests leave lingering sockets; a collision
hangs the rendezvous rather than failing fast).
"""

import os
import signal
import socket
import subprocess
import sys


def kill_proc_tree(proc):
    """SIGKILL a spawned worker's whole process group (it leads one:
    spawn_world starts each rank with ``start_new_session=True``), then
    the process itself as a fallback."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.kill()
    except OSError:
        pass


def scaled_timeout(seconds: float) -> float:
    """Spawn/rendezvous timeouts scaled by HVD_TPU_TEST_TIMEOUT_SCALE.

    Timeouts here are calibrated for an idle 1-core box; any
    contention (a parallel judge workload, concurrent shards) tips
    spawn-heavy tests into timeout flakes (r4: two such).  One knob
    scales every harness-level timeout rather than re-tuning each
    call site: ``HVD_TPU_TEST_TIMEOUT_SCALE=2 pytest ...``.
    """
    try:
        scale = float(os.environ.get("HVD_TPU_TEST_TIMEOUT_SCALE", "1"))
    except ValueError:
        scale = 1.0
    return seconds * max(scale, 0.1)


_SLOT_PORTS = 1200  # ports per (worker, shard) slot
_SLOT_COUNT = 31    # 27100 + 31*1200 = 64300 < 65535
_BASE_FLOOR = 27100


def _initial_port_base() -> int:
    # Disjoint ranges per pytest-xdist worker (and per run_sharded.py
    # shard): two processes probing the same base can both see a port
    # free (probe binds then closes) and collide when their spawned
    # worlds bind for real.  Slots are (worker + 8*shard) mod 31 —
    # collision-free for up to 8 workers x 3 shards concurrently on
    # one host (and any single dimension up to 31); beyond capacity
    # slots wrap, degrading to probe-time detection rather than
    # overflowing the 65535 port ceiling.
    worker = os.environ.get("PYTEST_XDIST_WORKER", "")
    idx = int(worker[2:]) if worker.startswith("gw") and \
        worker[2:].isdigit() else 0
    shard = os.environ.get("HVD_TPU_TEST_PORT_SHARD", "")
    if shard.isdigit():
        idx += int(shard) * 8
    return _BASE_FLOOR + (idx % _SLOT_COUNT) * _SLOT_PORTS


_port_base = [_initial_port_base()]


def free_port_block(size, extra_offsets=()):
    """A base where [base, base+size) plus any extra offsets bind."""
    hi = max(size, *extra_offsets) if extra_offsets else size
    for _ in range(200):
        _port_base[0] += size + 30
        # A long run can walk past the port ceiling — wrap back to the
        # slot floor (binds below still confirm actual freeness).
        if _port_base[0] + hi > 65000:
            _port_base[0] = _initial_port_base()
        base = _port_base[0]
        socks = []
        try:
            for port in ([base + i for i in range(size)]
                         + [base + o for o in extra_offsets]):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", port))
                socks.append(s)
            return base
        except (OSError, OverflowError):
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port block found")


def spawn_world(worker, size, extra_env=None, timeout=240, retry=True,
                extra_port_offsets=(), pop_env=()):
    """Run `worker` as `size` rank processes; returns [(rc, out, err)]."""
    timeout = scaled_timeout(timeout)
    base = free_port_block(size, extra_port_offsets)
    procs = []
    for rank in range(size):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        for key in pop_env:
            env.pop(key, None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(size),
            "HOROVOD_PORT_BASE": str(base),
        })
        # Default pin, caller-overridable: 1 ms negotiation cycles keep
        # spawn-heavy tests fast, but an explicit cycle-time env
        # legitimately suppresses the plan-cache tuned-point warm start
        # (env wins under the config precedence rule), so a world that
        # must model a default-config rerun names the key in
        # ``pop_env`` and gets a truly unset env — not a silent pin.
        # Every key pinned by this harness must be documented in
        # tests/README.md (the env-harness-pin lint check enforces it).
        if "HOROVOD_CYCLE_TIME" not in pop_env:
            env["HOROVOD_CYCLE_TIME"] = "1"
        env.update(extra_env or {})
        # Each rank leads its own process group (start_new_session) so
        # teardown can kill the whole tree: a worker that itself forked
        # (an elastic driver's children, a wedged grandchild) must not
        # outlive the test that spawned it.
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                kill_proc_tree(q)
            for q in procs:
                try:
                    q.communicate(timeout=10)
                except Exception:  # noqa: BLE001 - best-effort reap
                    pass
            if retry:
                return spawn_world(worker, size, extra_env, timeout,
                                   retry=False,
                                   extra_port_offsets=extra_port_offsets,
                                   pop_env=pop_env)
            raise
        outs.append((p.returncode, out.decode(), err.decode()))
    return outs


def assert_world_ok(outs, marker=None):
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, "rank %d failed (rc=%d):\n%s\n%s" % (rank, rc,
                                                             out, err)
        if marker is not None:
            assert "%s %d" % (marker, rank) in out, out
