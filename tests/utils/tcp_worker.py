"""Worker process for multi-process TCP core tests (run by
test_tcp_core.py as a real subprocess world, the way the reference tests
run under `horovodrun -np 2 pytest` with Gloo-on-localhost)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

from horovod_tpu.common.topology import multiprocess_topology
from horovod_tpu.common.config import Config
from horovod_tpu.core.client import TcpCore
from horovod_tpu.ops.engine import HorovodInternalError


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    scenario = os.environ.get("TEST_SCENARIO", "all")
    topo = multiprocess_topology(rank, size)
    core = TcpCore(topo, Config.from_env())
    core.initialize()
    try:
        if scenario in ("all", "collectives"):
            run_collectives(core, rank, size)
        if scenario in ("all", "cache"):
            run_cache(core, rank, size)
        if scenario == "big_allgather":
            run_big_allgather(core, rank, size)
        if scenario == "regroup":
            run_regroup(core, rank, size)
        if scenario == "cache_evict":
            run_cache_evict(core, rank, size)
        if scenario == "autotune":
            run_autotune(core, rank, size)
        if scenario == "join":
            run_join(core, rank, size)
        if scenario == "error":
            run_error(core, rank, size)
        if scenario == "deadline":
            run_deadline(core, rank, size)
    finally:
        core.shutdown()


def run_collectives(core, rank, size):
    # allreduce sum, fused small tensors.
    handles = []
    for i, n in enumerate((3, 5, 1000)):
        x = np.full((n,), float(rank + 1 + i), dtype=np.float32)
        handles.append(core.allreduce_async(x, "ar.%d" % i))
    for i, n in enumerate((3, 5, 1000)):
        out = handles[i].wait(timeout=30)
        expected = sum(r + 1 + i for r in range(size))
        assert out.shape == (n,), out.shape
        np.testing.assert_allclose(out, expected)
    # average with prescale/postscale.
    x = np.full((4,), float(rank), dtype=np.float64)
    out = core.allreduce_async(x, "avg", op="Average", prescale=2.0,
                               postscale=0.5).wait(timeout=30)
    np.testing.assert_allclose(
        out, 2.0 * np.mean(np.arange(size)) * 0.5)
    # min / max / product / int32.
    x = np.array([rank + 1], dtype=np.int32)
    assert core.allreduce_async(x, "min", op="Min").wait(30)[0] == 1
    assert core.allreduce_async(x, "max", op="Max").wait(30)[0] == size
    prod = core.allreduce_async(
        np.array([2.0], np.float32), "prod", op="Product").wait(30)
    np.testing.assert_allclose(prod, [2.0 ** size])
    # adasum (identical vectors collapse to one copy).
    same = np.arange(8, dtype=np.float32)
    out = core.allreduce_async(same, "adasum", op="Adasum").wait(30)
    np.testing.assert_allclose(out, same, rtol=1e-5)
    # allgather, ragged first dim: rank r contributes r+1 rows.
    x = np.full((rank + 1, 2), rank, dtype=np.float32)
    out = core.allgather_async(x, "ag").wait(timeout=30)
    assert out.shape == (sum(r + 1 for r in range(size)), 2)
    expected = np.concatenate(
        [np.full((r + 1, 2), r, np.float32) for r in range(size)])
    np.testing.assert_allclose(out, expected)
    # broadcast from root 1.
    x = (np.arange(6, dtype=np.float32).reshape(2, 3) if rank == 1
         else np.zeros((2, 3), np.float32))
    out = core.broadcast_async(x, "bc", root_rank=1).wait(timeout=30)
    np.testing.assert_allclose(
        out, np.arange(6, dtype=np.float32).reshape(2, 3))
    # alltoall with ragged splits: rank r sends (j+1) rows to rank j.
    splits = [j + 1 for j in range(size)]
    rows = sum(splits)
    x = np.full((rows, 2), rank, dtype=np.float32)
    out, recv_splits = core.alltoall_async(x, "a2a",
                                           splits=splits).wait(timeout=30)
    assert recv_splits == [rank + 1] * size, recv_splits
    assert out.shape == ((rank + 1) * size, 2)
    expected_col = np.repeat(np.arange(size, dtype=np.float32), rank + 1)
    np.testing.assert_allclose(out[:, 0], expected_col)
    # reducescatter with uneven first dim (size*2+1 rows).
    d0 = size * 2 + 1
    x = np.tile(np.arange(d0, dtype=np.float32)[:, None], (1, 3))
    out = core.reducescatter_async(x, "rs").wait(timeout=30)
    base, rem = divmod(d0, size)
    my_rows = base + (1 if rank < rem else 0)
    start = rank * base + min(rank, rem)
    assert out.shape == (my_rows, 3), out.shape
    np.testing.assert_allclose(
        out, size * np.tile(
            np.arange(start, start + my_rows,
                      dtype=np.float32)[:, None], (1, 3)))
    # barrier + process-set collective on even ranks.
    core.barrier("b1")
    ps = core.add_process_set(list(range(0, size, 2)))
    if rank % 2 == 0:
        x = np.full((3,), float(rank), np.float32)
        out = core.allreduce_async(x, "ps_ar", process_set_id=ps).wait(30)
        np.testing.assert_allclose(
            out, sum(float(r) for r in range(0, size, 2)))
    core.barrier("b2")
    # object helpers.
    objs = core.allgather_object({"rank": rank})
    assert [o["rank"] for o in objs] == list(range(size))
    obj = core.broadcast_object({"val": rank * 10}, root_rank=0)
    assert obj == {"val": 0}


def run_cache(core, rank, size):
    # Same tensor reduced repeatedly: second and later rounds must ride
    # the bitvector cache path (hits grow, misses stay flat).
    x = np.full((64,), float(rank), np.float32)
    core.allreduce_async(x, "steady").wait(30)
    h0, m0 = core.cache_stats() if rank == 0 else (0, 0)
    for it in range(5):
        out = core.allreduce_async(x, "steady").wait(30)
        np.testing.assert_allclose(out, sum(range(size)))
    if rank == 0:
        h1, m1 = core.cache_stats()
        assert h1 - h0 >= 5, (h0, h1)
        assert m1 == m0, (m0, m1)


def run_regroup(core, rank, size):
    # Group-name reuse with changed membership/shapes: grouped members
    # must not ride the response-cache bit path — a cached member would
    # complete solo while cache-missing groupmates wait on the group
    # barrier forever (the r3 deadlock this scenario regression-tests).
    def grouped(tensors):
        names = ["g.%d" % i for i in range(len(tensors))]
        core.register_group(names)
        hs = [core.allreduce_async(t, n) for t, n in zip(tensors, names)]
        return [h.wait(timeout=30) for h in hs]

    outs = grouped([np.ones(8, np.float32), np.ones((8, 4), np.float32),
                    np.ones((3, 8), np.float32)])
    for o in outs:
        np.testing.assert_allclose(o, float(size))
    # Same base name, fewer members, g.1 changes shape entirely.
    outs = grouped([np.ones(8, np.float32) * 2,
                    np.ones((2,), np.float32) * 2])
    for o in outs:
        np.testing.assert_allclose(o, 2.0 * size)
    # Steady-state reuse with identical layout still completes (grouped
    # names stay uncacheable; correctness over the bit path).
    for _ in range(3):
        outs = grouped([np.ones(8, np.float32), np.ones((2,), np.float32)])
        for o in outs:
            np.testing.assert_allclose(o, float(size))
    # Grouped allgather and reducescatter negotiate atomically too
    # (reference v0.28 grouped variants; ragged first member).
    names = ["gag.0", "gag.1"]
    core.register_group(names)
    hs = [core.allgather_async(
        np.full((rank + 1, 2), float(rank), np.float32), names[0]),
        core.allgather_async(np.full((3,), float(rank), np.float32),
                             names[1])]
    g0, g1 = [h.wait(timeout=30) for h in hs]
    assert g0.shape == (size * (size + 1) // 2, 2)
    assert g1.shape == (3 * size,)
    names = ["grs.0", "grs.1"]
    core.register_group(names)
    hs = [core.reducescatter_async(
        np.arange(size * 2, dtype=np.float32), names[0]),
        core.reducescatter_async(
            np.ones(size, np.float32) * (rank + 1), names[1])]
    r0, r1 = [h.wait(timeout=30) for h in hs]
    np.testing.assert_allclose(
        r0, np.arange(size * 2, dtype=np.float32)[
            rank * 2:(rank + 1) * 2] * size)
    np.testing.assert_allclose(r1, sum(range(1, size + 1)))
    if size >= 4:
        # Grouped collective scoped to a process set (even ranks):
        # atomic negotiation within the subgroup while odd ranks sit
        # out entirely.
        ps = core.add_process_set([0, 2])
        if rank in (0, 2):
            names = ["psg.0", "psg.1"]
            core.register_group(names)
            hs = [core.allreduce_async(
                np.ones(3, np.float32) * (rank + 1), names[0],
                process_set_id=ps),
                core.allreduce_async(np.ones(2, np.float32), names[1],
                                     process_set_id=ps)]
            o0, o1 = [h.wait(timeout=30) for h in hs]
            np.testing.assert_allclose(o0, 4.0)  # ranks 1 + 3
            np.testing.assert_allclose(o1, 2.0)
        core.barrier("psg_done")


def run_cache_evict(core, rank, size):
    # Capacity overflow: 10 rotating names against HOROVOD_CACHE_
    # CAPACITY=4 force constant LRU eviction + id reuse; correctness
    # requires every rank to assign/evict identically (broadcast
    # order), with a hot tensor pinned at the LRU front throughout.
    for round_ in range(6):
        hot = core.allreduce_async(
            np.full((8,), float(rank + round_), np.float32),
            "hot").wait(30)
        np.testing.assert_allclose(
            hot, sum(r + round_ for r in range(size)))
        for i in range(10):
            x = np.full((4,), float(rank + 1 + i), np.float32)
            out = core.allreduce_async(x, "rot.%d" % i).wait(30)
            np.testing.assert_allclose(
                out, sum(r + 1 + i for r in range(size)))
    # Shape change on a cached-then-evicted-then-reused name still
    # negotiates (LookupMatching guards shape).
    out = core.allreduce_async(
        np.full((2, 3), float(rank), np.float32), "rot.0").wait(30)
    np.testing.assert_allclose(out, sum(range(size)))


def run_autotune(core, rank, size):
    # steady allreduce traffic long enough for the BO autotuner to
    # complete several samples (pacing lowered via env in the test)
    x = np.full((4096,), float(rank), np.float32)
    for it in range(30):
        core.allreduce_async(x, "tune.%d" % (it % 3)).wait(30)


def run_big_allgather(core, rank, size):
    # multi-MB blocks: leader group exchange far exceeds socket
    # buffering, so only the ordered (parity) send/recv protocol
    # completes — guards the hierarchical-allgather deadlock case
    rows = 250_000  # 1 MB per rank (f32), 2-4 MB group payloads
    x = np.full((rows,), float(rank), np.float32)
    out = core.allgather_async(x, "big_ag").wait(timeout=120)
    assert out.shape == (rows * size,)
    for r in range(size):
        assert out[r * rows] == float(r)
        assert out[(r + 1) * rows - 1] == float(r)


def run_join(core, rank, size):
    # Uneven data: rank r has r+1 batches; after its last batch each rank
    # joins; allreduces keep working with joined ranks contributing zeros.
    for step in range(rank + 1):
        x = np.full((4,), 1.0, np.float32)
        core.allreduce_async(x, "j.%d.%d" % (rank, step))
    # Submit-then-join: every rank contributes real data to this Min
    # BEFORE joining (per-rank FIFO guarantees the request precedes the
    # join), so no zero-fill happens and the op must succeed.
    h = core.allreduce_async(np.full((4,), float(rank + 1), np.float32),
                             "jminok", op="Min")
    out = h.wait(timeout=120)
    assert np.allclose(out, 1.0), out
    if rank > 0 and size > 1:
        # Rank 0 has joined (or will before this becomes ready: it never
        # submits "jmin", so readiness requires its join).  Zero is not
        # Min's identity — the controller must error, not corrupt.
        h = core.allreduce_async(np.full((4,), 5.0, np.float32), "jmin",
                                 op="Min")
        try:
            h.wait(timeout=120)
            raise AssertionError("Min allreduce with joined rank "
                                 "should have errored")
        except HorovodInternalError as e:
            assert "Sum/Average" in str(e), str(e)
        # Average over the live contributors: rank 0 is joined and
        # missing, so the divisor is size-1, not size.
        h = core.allreduce_async(np.full((4,), float(rank), np.float32),
                                 "javg", op="Average")
        out = h.wait(timeout=120)
        expect = sum(range(1, size)) / float(size - 1)
        assert np.allclose(out, expect), (out, expect)
    # Everyone joins after its own work; join returns the last rank.
    last = core.join()
    assert 0 <= last < size


def run_deadline(core, rank, size):
    # A collective rank 0 submits but rank 1+ withholds: the native
    # core's per-collective deadline (HOROVOD_COLLECTIVE_TIMEOUT_SECS,
    # the C++ mirror of common/resilience.py) must error-complete it
    # with the RESTORE-shaped message — never the drain-shaped stall
    # text elastic keys on, and never a hang.
    import time
    budget = float(os.environ.get("HOROVOD_COLLECTIVE_TIMEOUT_SECS", "2"))
    if rank == 0:
        h = core.allreduce_async(np.ones(4, np.float32), "dl")
        try:
            h.wait(timeout=60)
            raise AssertionError("deadline should have expired")
        except HorovodInternalError as e:
            msg = str(e)
            assert "collective deadline exceeded" in msg, msg
            assert "stall shutdown threshold" not in msg, msg
    else:
        # Stay alive past rank 0's expiry so the world's teardown is
        # orderly (a dead peer would be a different failure mode).
        time.sleep(budget + 2.0)
    print("DEADLINE_OK %d" % rank, flush=True)


def run_error(core, rank, size):
    # Mismatched shapes across ranks must surface an error, not a hang.
    x = np.zeros((rank + 1,), np.float32)  # different shape per rank
    try:
        core.allreduce_async(x, "bad").wait(timeout=30)
        assert size == 1, "expected HorovodInternalError"
    except HorovodInternalError as e:
        assert "Mismatched" in str(e) or "shape" in str(e).lower()


if __name__ == "__main__":
    main()
