"""Worker for multi-rank TensorFlow adapter tests (real subprocess
world spawned by test_tf_adapter.py — the reference runs its TF suite
under ``horovodrun -np 2 pytest``, SURVEY.md §4).

Rank data is a deterministic function of rank, so every rank can
recompute the whole world's gradients locally and compare.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def rank_x(rank, n=8, d=4):
    g = np.random.RandomState(2000 + rank)
    return tf.constant(g.randn(n, d), dtype=tf.float32)


def make_weights(seed):
    g = np.random.RandomState(seed)
    return (tf.Variable(g.randn(4, 3).astype(np.float32)),
            tf.Variable(g.randn(3).astype(np.float32)))


def local_grads_np(w, b, x):
    """d/dw, d/db of mean((x @ w + b)^2), computed in numpy."""
    xn, wn, bn = x.numpy(), w.numpy(), b.numpy()
    y = xn @ wn + bn
    dy = 2.0 * y / y.size
    return xn.T @ dy, dy.sum(axis=0)


def run_tape(rank, size):
    w, b = make_weights(seed=7)
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_mean(tf.square(rank_x(rank) @ w + b))
    gw, gb = tape.gradient(loss, [w, b])

    per_rank = [local_grads_np(w, b, rank_x(r)) for r in range(size)]
    exp_w = np.mean([g[0] for g in per_rank], axis=0)
    exp_b = np.mean([g[1] for g in per_rank], axis=0)
    mine_w = local_grads_np(w, b, rank_x(rank))[0]
    assert np.allclose(gw.numpy(), exp_w, atol=1e-5), \
        "rank %d: tape grads do not match world mean" % rank
    assert np.allclose(gb.numpy(), exp_b, atol=1e-5)
    if size > 1:
        assert not np.allclose(gw.numpy(), mine_w, atol=1e-7), \
            "rank %d: tape grads identical to local grads" % rank


def run_grouped_tape(rank, size):
    # num_groups buckets the tape's gradients into atomic
    # grouped_allreduce calls; values must match the ungrouped world
    # mean exactly.
    w, b = make_weights(seed=7)
    with hvd.DistributedGradientTape(tf.GradientTape(),
                                     num_groups=2) as tape:
        loss = tf.reduce_mean(tf.square(rank_x(rank) @ w + b))
    gw, gb = tape.gradient(loss, [w, b])
    per_rank = [local_grads_np(w, b, rank_x(r)) for r in range(size)]
    assert np.allclose(gw.numpy(),
                       np.mean([g[0] for g in per_rank], axis=0),
                       atol=1e-5)
    assert np.allclose(gb.numpy(),
                       np.mean([g[1] for g in per_rank], axis=0),
                       atol=1e-5)
    # Explicit variable groups: w grouped (singleton), b individual.
    with hvd.DistributedGradientTape(tf.GradientTape(),
                                     groups=[[w]]) as tape:
        loss = tf.reduce_mean(tf.square(rank_x(rank) @ w + b))
    gw2, gb2 = tape.gradient(loss, [w, b])
    assert np.allclose(gw2.numpy(), gw.numpy(), atol=1e-6)
    assert np.allclose(gb2.numpy(), gb.numpy(), atol=1e-6)


def run_grouped_gradients(rank, size):
    # Grouped collectives are differentiable (the torch autograd parity
    # on the TF side): backward sums upstream grads across ranks.
    a = tf.Variable(tf.ones((2,)))
    b = tf.Variable(tf.ones((3,)))
    with tf.GradientTape() as tape:
        oa, ob = hvd.grouped_allreduce([a, b], op=hvd.Sum, name="tgar")
        loss = tf.reduce_sum(oa) + tf.reduce_sum(ob)
    ga, gb = tape.gradient(loss, [a, b])
    assert np.allclose(ga.numpy(), size * np.ones(2))
    assert np.allclose(gb.numpy(), size * np.ones(3))

    # Uneven first dims: rank r contributes r+1 rows to member 0 and a
    # fixed 2 rows to member 1 — exercises the per-member offset
    # arithmetic in the gradient's sizes matrix.
    c = tf.Variable(tf.fill((rank + 1, 2), float(rank + 1)))
    c2 = tf.Variable(tf.fill((2,), 3.0))
    with tf.GradientTape() as tape:
        g0, g1 = hvd.grouped_allgather([c, c2], name="tgag")
        loss = tf.reduce_sum(g0 * g0) + tf.reduce_sum(g1)
    gc, gc2 = tape.gradient(loss, [c, c2])
    assert int(g0.shape[0]) == size * (size + 1) // 2
    assert np.allclose(gc.numpy(), 2.0 * size * c.numpy(), atol=1e-5)
    assert np.allclose(gc2.numpy(), size * np.ones(2))

    d = tf.Variable(tf.ones((size * 2,)))
    with tf.GradientTape() as tape:
        (r0,) = hvd.grouped_reducescatter([d], op=hvd.Sum, name="tgrs")
        loss = tf.reduce_sum(r0)
    gd = tape.gradient(loss, d)
    assert np.allclose(gd.numpy(), np.ones(size * 2))


def run_sync_batch_norm(rank, size):
    # Synced BN over the global batch == local BN over the concatenated
    # batch, forward AND gradient (autodiff through the differentiable
    # allreduce).
    full = np.random.RandomState(5).randn(4 * size, 3).astype("float32")
    mine = tf.constant(full[rank * 4:(rank + 1) * 4])
    bn = hvd.SyncBatchNormalization(epsilon=1e-5)
    with tf.GradientTape() as tape:
        tape.watch(mine)
        out = bn(mine, training=True)
        loss = tf.reduce_sum(out * out)
    g = tape.gradient(loss, mine)

    # Local oracle on the concatenated batch.
    ref = tf.constant(full)
    gamma = tf.ones(3)
    beta = tf.zeros(3)
    with tf.GradientTape() as tape2:
        tape2.watch(ref)
        m, v = tf.nn.moments(ref, axes=[0])
        ro = (ref - m) * tf.math.rsqrt(v + 1e-5) * gamma + beta
        rl = tf.reduce_sum(ro * ro)
    rg = tape2.gradient(rl, ref)
    assert np.allclose(out.numpy(), ro.numpy()[rank * 4:(rank + 1) * 4],
                       atol=1e-4), "rank %d: synced BN forward" % rank
    assert np.allclose(g.numpy(), rg.numpy()[rank * 4:(rank + 1) * 4],
                       atol=1e-4), "rank %d: synced BN gradient" % rank
    # Moving stats absorbed the GLOBAL moments (both halves of the EMA).
    assert np.allclose(bn.moving_mean.numpy(), 0.01 * m.numpy(),
                       atol=1e-5)
    assert np.allclose(bn.moving_variance.numpy(),
                       0.99 * 1.0 + 0.01 * v.numpy(), atol=1e-5)
    # Frozen layer = inference mode: stats untouched.
    bn.trainable = False
    frozen_mean = bn.moving_mean.numpy().copy()
    bn(mine, training=True)
    assert np.allclose(bn.moving_mean.numpy(), frozen_mean)


def run_broadcast(rank, size):
    w, b = make_weights(seed=300 + rank)
    hvd.broadcast_variables([w, b], root_rank=0)
    ref_w, ref_b = make_weights(seed=300)
    assert np.allclose(w.numpy(), ref_w.numpy()), \
        "rank %d: broadcast_variables did not sync to root" % rank
    assert np.allclose(b.numpy(), ref_b.numpy())

    obj = hvd.broadcast_object({"epoch": 3, "rank": rank}
                               if rank == 0 else None, root_rank=0)
    assert obj == {"epoch": 3, "rank": 0}, \
        "rank %d: broadcast_object mismatch" % rank


def run_optimizer(rank, size):
    # Keras DistributedOptimizer: one apply_gradients must produce the
    # full-world-averaged update, identical on every rank.
    import keras
    w, b = make_weights(seed=12)
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.1))
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean(tf.square(rank_x(rank) @ w + b))
    grads = tape.gradient(loss, [w, b])
    opt.apply_gradients(zip(grads, [w, b]))

    per_rank = [local_grads_np(*make_weights(seed=12), x=rank_x(r))
                for r in range(size)]
    exp_w = np.mean([g[0] for g in per_rank], axis=0)
    ref_w, _ = make_weights(seed=12)
    assert np.allclose(w.numpy(), ref_w.numpy() - 0.1 * exp_w,
                       atol=1e-5), \
        "rank %d: optimizer update does not match world mean" % rank


def run_compression(rank, size):
    t = tf.constant([0.5 + rank, -1.25, 2.0], dtype=tf.float32)
    comp, ctx = hvd.Compression.fp16.compress(t)
    assert comp.dtype == tf.float16
    out = hvd.Compression.fp16.decompress(
        hvd.allreduce(comp, op=hvd.Average, name="tf_comp"), ctx)
    payloads = [np.array([0.5 + r, -1.25, 2.0], np.float16)
                for r in range(size)]
    expected = np.mean([p.astype(np.float32) for p in payloads], axis=0)
    assert out.dtype == tf.float32
    assert np.allclose(out.numpy(), expected, atol=1e-3), \
        "rank %d: fp16-compressed allreduce mismatch" % rank


def run_xla_ops(rank, size):
    # Native-op path (reference xla_mpi_ops.cc): eager CPU kernel, a
    # collective INSIDE tf.function(jit_compile=True), and the
    # registered gradient — all driving the real tcp core.
    from horovod_tpu.tensorflow import xla_ops
    if xla_ops.load() is None:
        raise RuntimeError("xla ops failed to load: %s"
                           % xla_ops._load_error)
    t = tf.constant([1.0 + rank, 2.0])
    expected = np.sum([[1.0 + r, 2.0] for r in range(size)], axis=0)
    # Eager stays on the mode's normal plane even with the knob set
    # (the native op only claims symbolic traces).
    out = hvd.allreduce(t, op=hvd.Sum, name="xla_eager")
    assert np.allclose(out.numpy(), expected), \
        "rank %d: eager allreduce mismatch" % rank

    # Plain tf.function: the native op's CPU kernel executes.
    @tf.function
    def graph_step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="xla_graph")

    out = graph_step(t)
    assert np.allclose(out.numpy(), expected), \
        "rank %d: graph-mode native-op allreduce mismatch" % rank

    # jit_compile=True: the XLA kernel lowers to the host custom call.
    @tf.function(jit_compile=True)
    def step(x):
        return hvd.allreduce(x * 2.0, op=hvd.Sum, name="xla_jit") + 1.0

    out = step(t)
    assert np.allclose(out.numpy(), expected * 2.0 + 1.0), \
        "rank %d: jit-compiled allreduce mismatch" % rank

    # Gradient through the registered native-op gradient, inside a
    # graph (symbolic trace -> native op on both fwd and bwd).
    v = tf.Variable([1.0 + rank, 3.0])

    @tf.function
    def grad_step():
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd.allreduce(v, op=hvd.Sum,
                                            name="xla_grad"))
        return tape.gradient(y, v)

    g = grad_step()
    assert np.allclose(g.numpy(), np.full(2, float(size))), \
        "rank %d: native-op gradient mismatch" % rank


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    try:
        assert hvd.rank() == rank and hvd.size() == size
        if os.environ.get("HOROVOD_ENABLE_XLA_OPS") == "1":
            run_xla_ops(rank, size)
        else:
            run_tape(rank, size)
            run_grouped_tape(rank, size)
            run_grouped_gradients(rank, size)
            run_sync_batch_norm(rank, size)
            run_broadcast(rank, size)
            run_optimizer(rank, size)
            run_compression(rank, size)
        print("TF_ADAPTER_OK %d" % rank)
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    main()
