"""Worker for multi-rank torch adapter tests (run as a real subprocess
world by test_torch_adapter.py, the way the reference runs its torch
suite under ``horovodrun -np 2 pytest`` — SURVEY.md §4).

Every check is against a locally recomputed cross-rank reference:
the data each rank feeds is a deterministic function of its rank, so
any rank can simulate the whole world in-process and compare.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import torch

import horovod_tpu.torch as hvd


def rank_data(rank, n=8, d=4):
    g = np.random.RandomState(1000 + rank)
    return torch.tensor(g.randn(n, d), dtype=torch.float32)


def make_model(seed):
    torch.manual_seed(seed)
    return torch.nn.Sequential(torch.nn.Linear(4, 3), torch.nn.ReLU(),
                               torch.nn.Linear(3, 2))


def local_grads(model, x):
    """Gradients of the mean-squared output on x, without mutating
    model.grad state."""
    params = [p for p in model.parameters()]
    loss = model(x).pow(2).mean()
    return torch.autograd.grad(loss, params)


def run_optimizer(rank, size):
    # All ranks start from identical weights; each feeds its own shard.
    model = make_model(seed=7)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())

    # The expected global gradient: mean over every rank's local grad,
    # recomputed here from scratch (any rank can simulate the world).
    ref_model = make_model(seed=7)
    per_rank = [local_grads(ref_model, rank_data(r)) for r in range(size)]
    expected = [torch.stack([g[i] for g in per_rank]).mean(0)
                for i in range(len(per_rank[0]))]
    mine = local_grads(ref_model, rank_data(rank))

    loss = model(rank_data(rank)).pow(2).mean()
    loss.backward()
    opt.synchronize()
    got = [p.grad.detach().clone() for p in model.parameters()]
    for g, e, m in zip(got, expected, mine):
        assert torch.allclose(g, e, atol=1e-5), \
            "rank %d: averaged grad does not match world mean" % rank
        if size > 1:
            assert not torch.allclose(g, m, atol=1e-7), \
                "rank %d: averaged grad identical to local grad" % rank

    with opt.skip_synchronize():
        opt.step()
    # After one SGD step every rank must hold identical weights equal to
    # the reference full-world update.
    ref_opt = torch.optim.SGD(ref_model.parameters(), lr=0.1)
    for p, e in zip(ref_model.parameters(), expected):
        p.grad = e.clone()
    ref_opt.step()
    for p, rp in zip(model.parameters(), ref_model.parameters()):
        assert torch.allclose(p, rp, atol=1e-6), \
            "rank %d: post-step weights diverge from reference" % rank


def run_broadcast(rank, size):
    # Rank-dependent init; after broadcast all ranks match rank 0's
    # deterministic weights (recomputable anywhere from the seed).
    model = make_model(seed=500 + rank)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    ref = make_model(seed=500)
    for p, rp in zip(model.state_dict().values(), ref.state_dict().values()):
        assert torch.allclose(p, rp), \
            "rank %d: broadcast_parameters did not sync to root" % rank

    # broadcast_optimizer_state: rank-dependent momentum buffers.
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss = model(rank_data(rank)).pow(2).mean()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1, momentum=0.9)
    ref_loss = ref(rank_data(0)).pow(2).mean()
    ref_loss.backward()
    ref_opt.step()
    state = opt.state_dict()["state"]
    ref_state = ref_opt.state_dict()["state"]
    for k in ref_state:
        for field, val in ref_state[k].items():
            if isinstance(val, torch.Tensor):
                assert torch.allclose(state[k][field], val, atol=1e-6), \
                    "rank %d: optimizer state %s/%s not synced" % (
                        rank, k, field)


def run_compression(rank, size):
    # fp16 wire compression round trip: compress -> allreduce the fp16
    # payload over the wire -> decompress back to fp32.
    t = torch.tensor([0.1 + rank, 1.5, -2.25, 3.0 + rank],
                     dtype=torch.float32)
    comp, ctx = hvd.Compression.fp16.compress(t)
    assert comp.dtype == torch.float16
    out = hvd.Compression.fp16.decompress(
        hvd.allreduce(comp, op=hvd.Average, name="comp"), ctx)
    payloads = [torch.tensor([0.1 + r, 1.5, -2.25, 3.0 + r]).half()
                for r in range(size)]
    expected = torch.stack([p.float() for p in payloads]).mean(0)
    assert torch.allclose(out, expected, atol=1e-3), \
        "rank %d: fp16-compressed allreduce mismatch" % rank
    assert out.dtype == torch.float32

    # And through the optimizer: grads ride the wire in fp16.
    model = make_model(seed=11)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16)
    loss = model(rank_data(rank)).pow(2).mean()
    loss.backward()
    opt.synchronize()
    ref_model = make_model(seed=11)
    per_rank = [local_grads(ref_model, rank_data(r)) for r in range(size)]
    expected = [torch.stack([g[i] for g in per_rank]).mean(0)
                for i in range(len(per_rank[0]))]
    for p, e in zip(model.parameters(), expected):
        assert torch.allclose(p.grad, e, atol=2e-3), \
            "rank %d: fp16-compressed optimizer grads mismatch" % rank


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    try:
        assert hvd.rank() == rank and hvd.size() == size
        run_optimizer(rank, size)
        run_broadcast(rank, size)
        run_compression(rank, size)
        print("TORCH_ADAPTER_OK %d" % rank)
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    main()
