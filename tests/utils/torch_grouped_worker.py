"""Worker: torch DistributedOptimizer grouped buckets + sparse grads.

Reference parity: ``horovod/torch/optimizer.py`` ``num_groups``/
``groups`` (gradient buckets negotiated atomically via
``grouped_allreduce``) and ``sparse_as_dense`` (sparse grads densified
before the wire).  Run under tests/utils/spawn.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np
import torch

import horovod_tpu.torch as hvd


def world_mean_grads(model, make_loss, size):
    """Recompute the expected averaged gradient: every rank's loss on
    its own data, averaged — evaluated locally by replaying all seeds."""
    grads = None
    state = [p.detach().clone() for p in model.parameters()]
    for r in range(size):
        for p, s in zip(model.parameters(), state):
            p.data.copy_(s)
            p.grad = None
        loss = make_loss(model, r)
        loss.backward()
        g = [p.grad.to_dense().clone() if p.grad.is_sparse
             else p.grad.clone() for p in model.parameters()]
        grads = g if grads is None else [a + b for a, b in zip(grads, g)]
    for p, s in zip(model.parameters(), state):
        p.data.copy_(s)
        p.grad = None
    return [g / size for g in grads]


def main():
    hvd.init()
    size, rank = hvd.size(), hvd.rank()

    # --- num_groups buckets keep replicas in lockstep -----------------
    torch.manual_seed(7)
    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 3),
        torch.nn.Tanh(), torch.nn.Linear(3, 2))
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    def make_loss(m, r):
        gen = torch.Generator().manual_seed(100 + r)
        x = torch.randn(6, 4, generator=gen)
        return m(x).pow(2).mean()

    expected = world_mean_grads(model, make_loss, size)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(), num_groups=2)
    assert len(opt._group_members) == 2
    assert sum(len(v) for v in opt._group_members.values()) == 6
    loss = make_loss(model, rank)
    loss.backward()
    opt.synchronize()
    for p, e in zip(model.parameters(), expected):
        np.testing.assert_allclose(p.grad.numpy(), e.numpy(), atol=1e-6)
    with opt.skip_synchronize():
        opt.step()
    opt.zero_grad()
    for h in opt._hook_handles:  # detach before re-wrapping the model
        h.remove()

    # --- explicit groups + ungrouped leftovers ------------------------
    params = list(model.parameters())
    expected = world_mean_grads(model, make_loss, size)
    opt2 = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        groups=[params[:2], params[2:4]])
    make_loss(model, rank).backward()
    opt2.step()  # step() synchronizes (individual + both groups)
    for p, e in zip(model.parameters(), expected):
        np.testing.assert_allclose(p.grad.numpy(), e.numpy(), atol=1e-6)
    opt2.zero_grad()
    for h in opt2._hook_handles:
        h.remove()

    # --- sparse embedding grads ride densified ------------------------
    torch.manual_seed(3)
    emb = torch.nn.Embedding(10, 4, sparse=True)
    hvd.broadcast_parameters(emb.state_dict(), root_rank=0)

    def emb_loss(m, r):
        idx = torch.tensor([r % 10, (r + 2) % 10, 3])
        return m[0](idx).sum() if isinstance(m, list) else m(idx).sum()

    class Wrap(torch.nn.Module):
        def __init__(self, e):
            super().__init__()
            self.e = e

        def forward(self, idx):
            return self.e(idx)

    wrap = Wrap(emb)
    expected = world_mean_grads(wrap, lambda m, r: emb_loss(m.e, r), size)
    opt3 = hvd.DistributedOptimizer(
        torch.optim.SGD(wrap.parameters(), lr=0.1),
        named_parameters=wrap.named_parameters(), sparse_as_dense=True)
    emb_loss(wrap.e, rank).backward()
    assert wrap.e.weight.grad.is_sparse
    opt3.synchronize()
    assert not wrap.e.weight.grad.is_sparse
    np.testing.assert_allclose(wrap.e.weight.grad.numpy(),
                               expected[0].numpy(), atol=1e-6)
    for h in opt3._hook_handles:
        h.remove()

    # Without sparse_as_dense, sparse grads ride the sparse wire
    # (indices/values allgather, reference sparse_allreduce_async) and
    # come back SPARSE and averaged.
    opt4 = hvd.DistributedOptimizer(
        torch.optim.SGD(wrap.parameters(), lr=0.1),
        named_parameters=wrap.named_parameters())
    wrap.e.weight.grad = None
    emb_loss(wrap.e, rank).backward()
    assert wrap.e.weight.grad.is_sparse
    opt4.synchronize()
    g = wrap.e.weight.grad
    assert g.is_sparse, "sparse wire must return a sparse grad"
    np.testing.assert_allclose(g.to_dense().numpy(),
                               expected[0].numpy(), atol=1e-6)
    for h in opt4._hook_handles:
        h.remove()

    # Direct sparse collective: disjoint and overlapping indices.
    sp = torch.sparse_coo_tensor(
        torch.tensor([[rank, 3]]), torch.tensor([1.0 + rank, 2.0]),
        (max(size, 4) + 4,))
    out = hvd.sparse_allreduce(sp, name="sp0", op=hvd.Sum)
    dense = out.to_dense()
    exp = np.zeros(max(size, 4) + 4, np.float32)
    for r in range(size):
        exp[r] += 1.0 + r
        exp[3] += 2.0
    np.testing.assert_allclose(dense.numpy(), exp, atol=1e-6)
    # Unnamed call: the deterministic auto-name counter negotiates
    # cross-rank (Average default divides by world size).
    out2 = hvd.sparse_allreduce(sp)
    np.testing.assert_allclose(out2.to_dense().numpy(), exp / size,
                               atol=1e-6)

    # Grouped allgather / reducescatter (reference v0.28 variants).
    g0, g1 = hvd.grouped_allgather(
        [torch.full((rank + 1, 2), float(rank)),
         torch.full((3,), float(rank))], name="tg")
    assert g0.shape == (size * (size + 1) // 2, 2)
    assert g1.shape == (3 * size,)
    r0, r1 = hvd.grouped_reducescatter(
        [torch.arange(size * 2, dtype=torch.float32),
         torch.ones(size) * (rank + 1)], name="tr")
    np.testing.assert_allclose(
        r0.numpy(),
        np.arange(size * 2, dtype=np.float32)[rank * 2:(rank + 1) * 2]
        * size)
    np.testing.assert_allclose(r1.numpy(), sum(range(1, size + 1)))

    # --- differentiable sync collectives (reference autograd
    # Functions); gradients follow the distributed contract: the
    # backward collective sums upstream grads across ranks ------------
    x = torch.arange(3, dtype=torch.float32, requires_grad=True)
    out = hvd.allreduce(x, op=hvd.Sum, name="dar")
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), size * np.ones(3),
                               atol=1e-6)

    x2 = (torch.arange(4, dtype=torch.float32).reshape(2, 2)
          * (rank + 1)).requires_grad_(True)
    g = hvd.allgather(x2, name="dag")
    assert g.shape == (2 * size, 2)
    (g * g).sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(),
                               2.0 * size * x2.detach().numpy(),
                               atol=1e-5)

    x3 = torch.ones(2, requires_grad=True)
    out = hvd.broadcast(x3, root_rank=0, name="dbc")
    (out * (rank + 1)).sum().backward()
    expected = (np.full(2, size * (size + 1) / 2.0) if rank == 0
                else np.zeros(2))
    np.testing.assert_allclose(x3.grad.numpy(), expected, atol=1e-6)

    x4 = torch.ones(size * 2, requires_grad=True)
    out = hvd.reducescatter(x4, op=hvd.Sum, name="drs")
    out.sum().backward()
    np.testing.assert_allclose(x4.grad.numpy(), np.ones(size * 2),
                               atol=1e-6)

    x5 = torch.arange(size, dtype=torch.float32).reshape(size, 1) \
        .requires_grad_(True)
    out, recv = hvd.alltoall(x5, splits=[1] * size, name="da2a")
    assert list(recv.numpy()) == [1] * size
    (out * (rank + 1)).sum().backward()
    np.testing.assert_allclose(
        x5.grad.numpy(),
        np.arange(1, size + 1, dtype=np.float32).reshape(size, 1),
        atol=1e-6)

    # Grouped variants are differentiable too.
    a = torch.ones(2, requires_grad=True)
    b = torch.ones(3, requires_grad=True)
    outs = hvd.grouped_allreduce([a, b], op=hvd.Sum, name="dgar")
    sum(o.sum() for o in outs).backward()
    np.testing.assert_allclose(a.grad.numpy(), size * np.ones(2))
    np.testing.assert_allclose(b.grad.numpy(), size * np.ones(3))
    c = (torch.arange(2, dtype=torch.float32) * (rank + 1)) \
        .requires_grad_(True)
    g0, = hvd.grouped_allgather([c], name="dgag")
    (g0 * g0).sum().backward()
    np.testing.assert_allclose(c.grad.numpy(),
                               2.0 * size * c.detach().numpy(),
                               atol=1e-5)
    d = torch.ones(size * 2, requires_grad=True)
    r0, = hvd.grouped_reducescatter([d], op=hvd.Sum, name="dgrs")
    r0.sum().backward()
    np.testing.assert_allclose(d.grad.numpy(), np.ones(size * 2))

    print("TORCH_GROUPED_OK", rank, flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
