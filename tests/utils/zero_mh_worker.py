"""Worker for the ZeRO-2/3 multihost e2e (ISSUE 15): a real 2-proc ×
2-local-device world runs both sharded step builders over the
proc×local mesh with the quantized DCN leg armed, asserts numerics
against a locally-computed single-device reference (position-dependent
payloads) within the error-feedback bounds, and — under
HVD_TPU_DUMP_HLO — asserts the lowered programs span all
n_procs×n_local partitions with real reduce-scatter/all-gather
collective HLO and an int8 wire."""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("TEST_LOCAL_DEVICES", "2")).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.jax.zero import make_zero2_step, make_zero3_step


def main():
    hvd.init(controller="multihost")
    r, n = hvd.rank(), hvd.size()
    n_local = int(os.environ.get("TEST_LOCAL_DEVICES", "2"))
    n_total = n * n_local
    assert jax.process_count() == n

    rng = np.random.RandomState(0)
    params = {"w": rng.randn(9, 4).astype(np.float32),  # 36: ragged
              "b": rng.randn(4).astype(np.float32)}
    gx = rng.randn(8 * n_total, 9).astype(np.float32)
    gy = rng.randn(8 * n_total, 4).astype(np.float32)
    per = gx.shape[0] // n  # this process's slice (position-dependent)
    batch_local = {"x": gx[r * per:(r + 1) * per],
                   "y": gy[r * per:(r + 1) * per]}

    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - b["y"]) ** 2)

    # single-device reference over the GLOBAL batch (known to all)
    opt = optax.adam(1e-2)
    ref_p, ref_s = params, opt.init(params)
    gbatch = {"x": gx, "y": gy}
    for _ in range(5):
        _loss, g = jax.value_and_grad(loss_fn)(ref_p, gbatch)
        u, ref_s = opt.update(g, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, u)

    wire = os.environ.get("HOROVOD_CROSS_HOST_COMPRESSION", "int8")
    tol = 5e-3 if wire in ("int8", "fp8") else 1e-4

    def check(tree, what, bound):
        for k in params:
            err = float(np.max(np.abs(
                np.asarray(tree[k]) - np.asarray(ref_p[k]))))
            assert err < bound, (what, k, err)

    def hlo_of(step, *args):
        for cell in step.__closure__ or ():
            val = cell.cell_contents
            if isinstance(val, dict) and "step" in val:
                return val["step"].lower(*args).compile().as_text()
        raise AssertionError("compiled step not found")

    # -- zero-2: gradient reduce-scatter on the quantized DCN leg ----
    step2, init2 = make_zero2_step(loss_fn, optax.adam(1e-2))
    zp = hvd.replicate(params)
    carry = init2(zp)
    assert carry["ef"], "EF residuals missing (codec did not engage)"
    zb = hvd.shard_batch(batch_local)
    if os.environ.get("HVD_TPU_DUMP_HLO"):
        txt = hlo_of(step2, zp, carry, zb)
        import re
        parts = sorted(set(re.findall(r"num_partitions\s*=\s*(\d+)",
                                      txt)))
        assert ("num_partitions = %d" % n_total) in txt \
            or ("num_partitions=%d" % n_total) in txt, \
            "zero-2 program does not span all %d devices " \
            "(num_partitions markers: %s; head: %r)" \
            % (n_total, parts, txt[:300])
        assert "reduce-scatter" in txt or "reduce_scatter" in txt, txt[:200]
        assert "all-gather" in txt or "all_gather" in txt
        if wire == "int8":
            assert "s8[" in txt, "no int8 wire in the zero-2 HLO"
    for _ in range(5):
        zp, carry, _ = step2(zp, carry, zb)
    check(hvd.fetch(zp), "zero2", tol)
    print("ZERO2_OK rank=%d" % r, flush=True)

    # -- zero-3: param gather-on-demand + grad reduce-scatter --------
    step3, init3, gather3 = make_zero3_step(loss_fn, optax.adam(1e-2))
    state = init3(hvd.replicate(params))
    if os.environ.get("HVD_TPU_DUMP_HLO"):
        txt = hlo_of(step3, state, zb)
        assert ("num_partitions = %d" % n_total) in txt \
            or ("num_partitions=%d" % n_total) in txt, \
            "zero-3 program does not span all %d devices" % n_total
        assert "all-gather" in txt or "all_gather" in txt
    for _ in range(5):
        state, _ = step3(state, zb)
    check(hvd.fetch(gather3(state)), "zero3", 2e-2)
    print("ZERO3_OK rank=%d" % r, flush=True)

    hvd.shutdown()
    print("MULTIHOST_OK %d" % r, flush=True)


if __name__ == "__main__":
    main()
