"""Same-minute A/B: current NormAct ResNet vs an old-style flax-BN ResNet."""
import time, numpy as np, jax, jax.numpy as jnp, optax
import flax.linen as nn
from functools import partial
from typing import Any, Callable, Tuple
from horovod_tpu.models.resnet import create_resnet50, resnet_loss_fn, STAGE_SIZES

# --- old-style model (pre-rewrite structure) ---
class OldBottleneck(nn.Module):
    filters: int; strides: Tuple[int, int]; norm: Callable; dtype: Any = jnp.bfloat16
    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y); y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False, dtype=self.dtype)(y)
        y = self.norm()(y); y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides, use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)

class OldResNet(nn.Module):
    dtype: Any = jnp.bfloat16
    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False, dtype=self.dtype)(x)
        x = norm()(x); x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, nb in enumerate(STAGE_SIZES[50]):
            for j in range(nb):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = OldBottleneck(64 * 2 ** i, strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(1000, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)

def bench_model(model, loss_fn, tag, batch=128, image=224, steps=30):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, image, image, 3), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    bd = {"x": x, "y": y}
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, image, image, 3), np.float32), train=True)
    params, stats = v["params"], v.get("batch_stats", {})
    tx = optax.sgd(0.1, momentum=0.9)
    os_ = tx.init(params)
    def train_step(p, bs, o, b):
        def loss(pp):
            nll, new = loss_fn(model, {"params": pp, "batch_stats": bs}, b)
            return nll, new.get("batch_stats", bs)
        (nll, nbs), g = jax.value_and_grad(loss, has_aux=True)(p)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), nbs, o, nll
    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    fetch = jax.jit(lambda v: v.astype(jnp.float32))
    def run(n, p, bs, o):
        t0 = time.perf_counter()
        nll = None
        for _ in range(n):
            p, bs, o, nll = step(p, bs, o, bd)
        float(np.asarray(fetch(nll)))
        return time.perf_counter() - t0, p, bs, o
    _, params, stats, os_ = run(5, params, stats, os_)
    t1s, t2s = [], []
    for _ in range(3):
        t1, params, stats, os_ = run(steps, params, stats, os_)
        t2, params, stats, os_ = run(2 * steps, params, stats, os_)
        t1s.append(t1); t2s.append(t2)
    dt = min(t2s) - min(t1s)
    print("%s: %.2f img/s  %.3f ms/step" % (tag, batch * steps / dt, dt / steps * 1e3), flush=True)

def old_loss(model, variables, batch, train=True):
    logits, new = model.apply(variables, batch["x"], train=True, mutable=["batch_stats"])
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, batch["y"][:, None], axis=1).mean(), new

bench_model(create_resnet50(), resnet_loss_fn, "NormAct(cold)")
bench_model(create_resnet50(), resnet_loss_fn, "NormAct(hot)")
